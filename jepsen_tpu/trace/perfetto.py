"""Streaming Perfetto/Chrome Trace Event sink.

Writes the JSON *array* form of the Trace Event Format — ``[`` then one
event object per line, comma-terminated. Emission is ASYNCHRONOUS: hot
callers (the interpreter's scheduler) pay one deque append; a
background writer thread drains the queue every
:data:`FLUSH_INTERVAL_S`, expands compact op tuples (same
:func:`~jepsen_tpu.trace.flight.expand_op_event` the flight recorder
dumps through — one schema), serializes, writes, and flushes. The
serialization cost runs while the scheduler is parked in its own queue
waits, and the file's complete-line prefix trails the run by at most
one flush interval, so a SIGKILL'd run still leaves a loadable trace
(Perfetto's and Chrome's JSON importers both accept an unterminated
array; :func:`read_trace_events` is the same tolerant reader for our
own tooling). A clean :meth:`close` drains everything and appends the
``]`` terminator, making the file strictly valid JSON.

Tracks: the tracer's logical track names map to (pid 1, tid n) lanes;
each track's first event is preceded by a ``thread_name`` metadata
event so Perfetto labels the lane.
"""
from __future__ import annotations

import json
import logging
import threading
import time
from collections import deque
from pathlib import Path

from jepsen_tpu.trace.flight import expand_op_event

logger = logging.getLogger("jepsen.trace.perfetto")

PID = 1
FLUSH_INTERVAL_S = 0.1
WRITER_JOIN_S = 5.0
# events serialized per GIL-holding stretch: the writer yields between
# chunks so a big backlog can't stall the scheduler for a full drain
DRAIN_CHUNK = 512


class PerfettoSink:
    """Append-only ``trace.json`` writer with a background drain
    thread. ``emit`` never raises and never blocks on I/O — a dying
    trace file must not take down the run it observes (the WAL's
    contract)."""

    def __init__(self, path, flush_interval_s: float = FLUSH_INTERVAL_S):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._q: deque = deque()
        self._tids: dict[str, int] = {}
        # writer-side memo tables for the hot op-tuple shapes: worker ->
        # registered tid, f/process -> their JSON encodings (op streams
        # draw from tiny vocabularies, so each encodes once)
        self._worker_tids: dict = {}
        self._json_memo: dict = {}
        self._events = 0
        self._broken = False
        # wall-us minus relative-us at run start (see FlightRecorder)
        self.op_origin_us: int | None = None
        self._lock = threading.Lock()  # serializes drains (writer/close)
        self._stop = threading.Event()
        self._f = open(self.path, "w", encoding="utf-8")
        self._f.write("[\n")
        self._f.flush()
        self._writer = threading.Thread(
            target=self._writer_loop, daemon=True,
            name="jepsen-trace-writer",
            args=(flush_interval_s,))
        self._writer.start()

    def emit(self, ev) -> None:
        """One tracer event — a full dict ({ph, track, name, ts, ...})
        or a compact op tuple — onto the write queue; the writer owns
        expansion, the pid/tid mapping, and the file. The append is
        deliberately lockless: deque.append is GIL-atomic, and the
        lock below only serializes the drain side (writer vs close)."""
        self._q.append(ev)  # lint: ignore[lock-guard]

    def appender(self):
        """The raw bound queue append for single-writer hot paths
        (the flight recorder's ``appender`` twin)."""
        return self._q.append

    def _writer_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            while self._drain():
                time.sleep(0)  # yield between chunks (GIL fairness)
        while self._drain():  # close() signaled: sweep the backlog
            pass

    def _track_tid(self, track: str, lines: list[str]) -> int:
        """The track's tid, appending its thread_name metadata line on
        first use. Caller holds the lock."""
        tid = self._tids.get(track)
        if tid is None:
            tid = self._tids[track] = len(self._tids) + 1
            lines.append(json.dumps(
                {"ph": "M", "name": "thread_name", "pid": PID,
                 "tid": tid, "args": {"name": track}}))
        return tid

    def _jmemo(self, value) -> str:
        j = self._json_memo.get(value)
        if j is None:
            j = self._json_memo[value] = json.dumps(value, default=str)
        return j

    def _op_line(self, ev: tuple, lines: list[str]) -> str | None:
        """One compact op tuple -> its JSON line, formatted directly —
        no intermediate dict, memoized f/process encodings. This is
        the writer's hot loop: op events dominate a trace, and the
        direct format keeps the writer thread's GIL share (which the
        scheduler competes with) to a fraction of json.dumps'.
        Dispatch (B) tuples are flight-ring context only — the
        completion's self-contained X slice covers the op here, so a
        trace.json never pays two events per op. Falls back to the
        shared dict expansion for odd shapes (an error'd completion, a
        non-literal time)."""
        if ev[0] == "B":
            return None  # subsumed by the completion's X slice
        _, worker, comp, t0 = ev
        end = comp.get("time")
        if not isinstance(t0, int) or not isinstance(end, int) \
                or comp.get("error") is not None:
            ev2 = expand_op_event(ev, self.op_origin_us)
            if ev2 is None:
                return None
            from jepsen_tpu.trace import worker_track
            out = {k: v for k, v in ev2.items() if k != "track"}
            out["pid"] = PID
            out["tid"] = self._track_tid(worker_track(worker), lines)
            return json.dumps(out, default=str)
        ts = t0 // 1000
        origin = self.op_origin_us
        if origin is not None:
            ts += origin
        dur = (end - t0) // 1000
        if dur < 1:
            dur = 1
        wt = self._worker_tids.get(worker)
        if wt is None:
            from jepsen_tpu.trace import worker_track
            wt = self._worker_tids[worker] = self._track_tid(
                worker_track(worker), lines)
        name_j = self._jmemo(str(comp.get("f")))
        proc = comp.get("process")
        return (f'{{"ph":"X","pid":1,"tid":{wt},"ts":{ts},"dur":{dur},'
                f'"name":{name_j},"args":{{"process":{self._jmemo(proc)},'
                f'"f":{name_j},"type":{self._jmemo(comp.get("type"))},'
                f'"trace_id":"{proc}-{t0}"}}}}')

    def _drain(self) -> bool:
        """Serializes and writes up to DRAIN_CHUNK queued events.
        Returns True when a backlog remains (the writer yields and
        comes straight back), False when the queue is drained."""
        with self._lock:
            if self._broken or self._f.closed or not self._q:
                return False
            lines: list[str] = []
            try:
                for _ in range(DRAIN_CHUNK):
                    try:
                        ev = self._q.popleft()
                    except IndexError:
                        break
                    if isinstance(ev, tuple):
                        line = self._op_line(ev, lines)
                        if line is not None:
                            lines.append(line)
                            self._events += 1
                        continue
                    tid = self._track_tid(ev.get("track", "run"), lines)
                    out = {k: v for k, v in ev.items() if k != "track"}
                    out["pid"] = PID
                    out["tid"] = tid
                    lines.append(json.dumps(out, default=str))
                    self._events += 1
                if lines:
                    # one write + one flush per batch: the kernel page
                    # cache survives a SIGKILL, so the loadable prefix
                    # trails the run by at most one flush interval
                    self._f.write(",\n".join(lines) + ",\n")
                    self._f.flush()
            except (OSError, ValueError, TypeError):
                logger.exception("trace.json write failed; span sink off "
                                 "for the rest of the run")
                self._broken = True
                try:
                    self._f.close()
                except OSError:
                    pass
                return False
            return bool(self._q)

    @property
    def events(self) -> int:
        with self._lock:
            return self._events

    def close(self) -> None:
        """Drains the queue, terminates the array — a final comma-less
        marker event then ``]`` — and closes. Idempotent; a crashed run
        that never gets here still loads (the terminator is optional in
        the Trace Event Format, and :func:`read_trace_events` parses
        per-line either way)."""
        self._stop.set()
        self._writer.join(timeout=WRITER_JOIN_S)
        self._drain()
        with self._lock:
            if self._f.closed:
                return
            try:
                self._f.write(json.dumps(
                    {"ph": "M", "name": "trace_done", "pid": PID,
                     "tid": 0, "args": {"events": self._events}})
                    + "\n]\n")
                self._f.flush()
            except (OSError, ValueError):
                logger.exception("trace.json terminator write failed")
            try:
                self._f.close()
            except OSError:
                pass


def read_trace_events(path, max_bytes: int | None = None) -> list[dict]:
    """Tolerant Trace Event reader: parses the per-line array this sink
    writes (terminated or not), dropping a torn final line — the same
    valid-prefix contract the WAL reader gives history. ``max_bytes``
    bounds the read for summary rendering over huge traces."""
    p = Path(path)
    with open(p, encoding="utf-8", errors="replace") as f:
        data = f.read(max_bytes) if max_bytes else f.read()
    events: list[dict] = []
    for line in data.splitlines():
        line = line.strip().rstrip(",")
        if not line or line in ("[", "]"):
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue  # torn tail (or a mid-read cut at max_bytes)
        if isinstance(ev, dict):
            events.append(ev)
    return events
