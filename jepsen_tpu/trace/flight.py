"""The flight recorder: a bounded in-memory ring of recent trace events.

The *default* run pays for no trace file — but a wedge or crash with no
trace is undiagnosable. This ring keeps the most recent N events (the
last ~seconds of causal context: dispatches, completions, reaps, fault
windows, ladder demotions) at near-zero cost, and is dumped to
``flight-recorder.jsonl`` only when something goes wrong: the
interpreter's stall watchdog, core.run's fatal path, or the atexit
crash hook (doc/observability.md "Causal trace").

Lock-free-ish by design: the ring IS a ``collections.deque(maxlen=N)``
— append is one C call, eviction of the oldest event is native, and
the GIL serializes concurrent emitters. The interpreter's op fast path
(:meth:`appender` — the telemetry ``cell()`` analog) appends raw
``(kind, worker, op-dict-reference)`` tuples with no dict build, no
timestamp read, and no id mint; ALL derivable work (track name, trace
id via :func:`trace_id_for`, wall timestamps from the op's own
relative time + the run's one-shot origin) is deferred to
:func:`expand_op_event` at dump time.
"""
from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from pathlib import Path

logger = logging.getLogger("jepsen.trace.flight")

# compact op-tuple kinds (the scheduler's single-writer fast path):
# a 3-tuple (OP_BEGIN, worker, op) at dispatch — flight-ring only, the
# in-flight context a crash dump needs — and a 4-tuple (OP_COMPLETE,
# worker, completion, invoke_time_ns) at completion, which both sinks
# render as one self-contained slice (invoke -> completion)
OP_BEGIN = "B"
OP_COMPLETE = "X"


class FlightRecorder:
    """Fixed-capacity event ring: exactly the most recent ``capacity``
    events survive (deque maxlen semantics — wraparound is native and
    exact)."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError("flight recorder needs capacity >= 1")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        # wall-us minus relative-us at run start; set once by the
        # interpreter so dump timestamps land on the wall clock
        self.op_origin_us: int | None = None

    def record(self, ev) -> None:
        """A full event dict (instants, windows, rung slices) or a
        compact op tuple."""
        self._ring.append(ev)

    def appender(self):
        """The raw bound ``deque.append`` — the single-writer hot-path
        handle (telemetry's ``cell()`` pattern): the interpreter's
        scheduler appends op tuples through this with one C call."""
        return self._ring.append

    @property
    def recorded(self) -> int:
        """Events currently retained (capacity-capped)."""
        return len(self._ring)

    def snapshot(self) -> list:
        """Events oldest->newest. Exact when writers are quiescent
        (dumps happen on stalls/crashes); a concurrent writer can at
        worst add/evict an event mid-copy."""
        return list(self._ring)

    def dump(self, path, reason: str) -> bool:
        """Writes the ring to ``path`` as jsonl — a header row naming
        the trigger, then the retained events oldest-first (op tuples
        expanded to full events) — flushed and fsynced (this file is
        written precisely when the process may be about to die).
        Appends, so a stall dump followed by a crash dump keeps both.
        Returns True on success; never raises."""
        events = self.snapshot()
        try:
            p = Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            with open(p, "a", encoding="utf-8") as f:
                f.write(json.dumps({
                    "flight_recorder": True, "reason": reason,
                    "dumped_at": time.time(), "capacity": self.capacity,
                    "retained": len(events),
                    "timebase": ("wall-us" if self.op_origin_us is not None
                                 else "relative-us"),
                }) + "\n")
                # a dispatch (B) tuple whose op later completed inside
                # the ring is subsumed by its X slice — keep B only for
                # ops still in flight (the context a crash dump is FOR)
                completed = {(ev[1], ev[3]) for ev in events
                             if isinstance(ev, tuple) and len(ev) == 4}
                for ev in events:
                    if isinstance(ev, tuple):
                        if ev[0] == OP_BEGIN and \
                                (ev[1], ev[2].get("time")) in completed:
                            continue
                        ev = expand_op_event(ev, self.op_origin_us)
                    if ev is None:
                        continue
                    f.write(json.dumps(ev, default=str) + "\n")
                f.flush()
                os.fsync(f.fileno())
            logger.warning("flight recorder dumped %d event(s) to %s "
                           "(reason: %s)", len(events), p, reason)
            return True
        except Exception:  # noqa: BLE001 — a crash dump must never raise
            logger.exception("flight-recorder dump to %s failed", path)
            return False


def expand_op_event(ev: tuple, origin_us: int | None) -> dict | None:
    """One compact op tuple -> the full event dict, identical in shape
    to what a synchronous emitter would have produced (same
    track/name/args/trace-id), so the flight dump and trace.json speak
    one schema. Timestamps: the op's own relative nanoseconds shifted
    by the run's one-shot ``origin_us`` (relative-only when the origin
    was never captured — ordering still holds)."""
    from jepsen_tpu.trace import trace_id_for, worker_track
    try:
        track = worker_track(ev[1])
        if ev[0] == OP_BEGIN:
            _, _, op = ev
            t = op.get("time")
            ts = int(t) // 1000 if isinstance(t, (int, float)) else 0
            if origin_us is not None:
                ts += origin_us
            return {"ph": "B", "track": track,
                    "name": str(op.get("f")), "ts": ts,
                    "args": {"process": op.get("process"),
                             "f": str(op.get("f")),
                             "trace_id": trace_id_for(op.get("process"),
                                                      t)}}
        _, _, comp, t0 = ev
        end = comp.get("time")
        if not isinstance(t0, (int, float)):
            t0 = end if isinstance(end, (int, float)) else 0
        ts = int(t0) // 1000
        if origin_us is not None:
            ts += origin_us
        dur = (max(int(end - t0) // 1000, 1)
               if isinstance(end, (int, float)) else 1)
        args = {"process": comp.get("process"),
                "f": str(comp.get("f")),
                "type": comp.get("type"),
                "trace_id": trace_id_for(comp.get("process"), int(t0))}
        if comp.get("error") is not None:
            args["error"] = str(comp.get("error"))
        return {"ph": "X", "track": track, "name": str(comp.get("f")),
                "ts": ts, "dur": dur, "args": args}
    except Exception:  # noqa: BLE001 — one bad tuple can't kill a dump
        logger.exception("couldn't expand op trace tuple")
        return None
