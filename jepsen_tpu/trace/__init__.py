"""Run-wide causal tracing: one span stream per run, two sinks.

:mod:`jepsen_tpu.tracing` (the dgraph ``trace.clj`` analog) spans client
ops only. This package is the run-WIDE half (doc/observability.md
"Causal trace"): every timeline a run produces — interpreter dispatch
per worker, nemesis fault windows from the durable registry, checker
ladder rung attempts and demotions, segmented-check segments and
checkpoint writes/resumes, mesh shrinks, live-daemon polls, WAL fsyncs —
emits events into one per-run :class:`RunTracer`, causally linked by a
**stable trace id** minted at interpreter dispatch
(:func:`trace_id_for`). The id is a pure function of the op's
``(process, invoke-time)``, both of which the WAL/history already
persist, so the id survives the run with no schema change and offline
tooling (:mod:`jepsen_tpu.trace.derive`) re-derives the identical ids
retroactively.

Two sinks, independently enabled:

* :class:`~jepsen_tpu.trace.perfetto.PerfettoSink` — a streaming
  Perfetto/Chrome ``trace.json`` (Trace Event Format), one event per
  line, flushed per event so a SIGKILL'd run still leaves a loadable
  array prefix. On at ``--trace`` verbosity (``trace`` knob /
  ``JEPSEN_TPU_TRACE``).
* :class:`~jepsen_tpu.trace.flight.FlightRecorder` — an always-on
  bounded in-memory ring of the most recent events, dumped to
  ``flight-recorder.jsonl`` by the stall watchdog, fatal run paths
  (``PreflightFailed`` exempt — a rejected test map is not a crash),
  and an atexit crash hook. ``flight_recorder_events`` /
  ``JEPSEN_TPU_FLIGHT_RECORDER_EVENTS`` sizes it; ``0`` disables.

Zero-cost disabled mode, telemetry-style: the module default is
:data:`NULL_TRACER` whose every method is a constant no-op, and call
sites guard hot blocks on ``tracer.enabled``. ``core.run`` installs a
live tracer per run and restores the previous one after.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import contextmanager

from jepsen_tpu.trace.flight import OP_BEGIN, OP_COMPLETE, FlightRecorder
from jepsen_tpu.trace.perfetto import PerfettoSink

logger = logging.getLogger("jepsen.trace")

TRACE_NAME = "trace.json"
FLIGHT_NAME = "flight-recorder.jsonl"

DEFAULT_FLIGHT_EVENTS = 4096

# Track naming convention (lint-enforced for literals, JTM001): kebab-case.
# Worker tracks are dynamic ("worker-0".."worker-N"); the nemesis worker's
# track is "nemesis" so fault ops and fault windows share a lane.
TRACK_SCHEDULER = "scheduler"
TRACK_NEMESIS = "nemesis"
TRACK_CHECKER = "checker"
TRACK_LADDER = "checker-ladder"
TRACK_CHECKPOINT = "checkpoint"
TRACK_LIVE = "live"
TRACK_WAL = "wal"

TRACKS = (TRACK_SCHEDULER, TRACK_NEMESIS, TRACK_CHECKER,
          TRACK_LADDER, TRACK_CHECKPOINT, TRACK_LIVE, TRACK_WAL)


def worker_track(worker_id) -> str:
    """The per-worker track name; the nemesis worker gets its own lane
    (``worker_id`` is the interpreter's NEMESIS sentinel there)."""
    if isinstance(worker_id, int):
        return f"worker-{worker_id}"
    return TRACK_NEMESIS


def trace_id_for(process, time_ns) -> str:
    """The stable trace id of one history-bound op: a pure function of
    its ``(process, invoke-time-ns)`` pair — minted at interpreter
    dispatch, re-derivable from any artifact that persists those two
    fields (the WAL record, history.jsonl, a quarantined late
    completion). Process renumbering makes the pair unique per run:
    one process never has two ops in flight. Deliberately a plain
    format, not a hash: the id is an identity, cheap enough for the
    dispatch hot path, and a human reading a trace can see which
    process/op it names."""
    return f"{process}-{time_ns}"


def now_us() -> int:
    """Trace-event timestamp: wall-clock microseconds (the Trace Event
    Format's ``ts`` unit)."""
    return time.time_ns() // 1000


class RunTracer:
    """One run's span stream. Thread-safe: the interpreter scheduler,
    worker threads, the nemesis thread, checker watchdog threads and
    the live daemon's poller all emit concurrently; each sink serializes
    internally. Event building happens only when a sink is attached
    (``enabled``), so the disabled path costs one attribute read."""

    def __init__(self, perfetto: PerfettoSink | None = None,
                 flight: FlightRecorder | None = None):
        self.perfetto = perfetto
        self.flight = flight
        self.enabled = perfetto is not None or flight is not None
        self._crash_path = None
        self._closed = False
        self._lock = threading.Lock()

    # -- emission ---------------------------------------------------------

    def _emit(self, ev: dict) -> None:
        p, fl = self.perfetto, self.flight
        if p is not None:
            p.emit(ev)
        if fl is not None:
            fl.record(ev)

    # -- the interpreter's single-writer fast path ------------------------

    def set_op_origin(self, origin_us: int) -> None:
        """One-shot clock pairing (wall-us minus relative-us at run
        start), captured by the interpreter before its loop: op tuples
        carry only the op's relative time, and the sinks shift them
        onto the wall clock with this at expansion time — so the hot
        path never reads a clock at all."""
        if self.perfetto is not None:
            self.perfetto.op_origin_us = origin_us
        if self.flight is not None:
            self.flight.op_origin_us = origin_us

    def op_sink(self):
        """The scheduler's op-event appender (telemetry's ``cell()``
        analog): a callable taking one compact op tuple —
        ``(OP_BEGIN, worker, op)`` at dispatch, ``(OP_COMPLETE,
        worker, completion, invoke_time_ns)`` at completion.
        Flight-only runs (the default) get the ring's raw
        ``deque.append``; with a Perfetto sink attached the tuple fans
        out to both. None when tracing is off."""
        p, fl = self.perfetto, self.flight
        if p is not None and fl is not None:
            p_append, f_append = p.appender(), fl.appender()

            def both(ev) -> None:
                p_append(ev)
                f_append(ev)
            return both
        if p is not None:
            return p.appender()
        if fl is not None:
            return fl.appender()
        return None

    def begin(self, track: str, name: str, args: dict | None = None,
              ts_us: int | None = None) -> None:
        """Opens a duration slice on ``track`` (Trace Event ``B``). One
        slice may be open per track at a time — the interpreter's
        one-op-in-flight-per-worker invariant."""
        if not self.enabled:
            return
        self._emit({"ph": "B", "track": track, "name": name,
                    "ts": now_us() if ts_us is None else ts_us,
                    "args": args or {}})

    def end(self, track: str, args: dict | None = None,
            ts_us: int | None = None) -> None:
        """Closes the open slice on ``track`` (Trace Event ``E``)."""
        if not self.enabled:
            return
        self._emit({"ph": "E", "track": track,
                    "ts": now_us() if ts_us is None else ts_us,
                    "args": args or {}})

    def complete(self, track: str, name: str, start_us: int, dur_us: int,
                 args: dict | None = None) -> None:
        """A self-contained slice (Trace Event ``X``): emitted once at
        completion, so interleaving emitters (watchdog-abandoned rungs,
        overlapping daemon polls) can never tear a B/E pairing."""
        if not self.enabled:
            return
        self._emit({"ph": "X", "track": track, "name": name,
                    "ts": start_us, "dur": max(int(dur_us), 1),
                    "args": args or {}})

    def instant(self, track: str, name: str, args: dict | None = None,
                ts_us: int | None = None) -> None:
        if not self.enabled:
            return
        self._emit({"ph": "i", "track": track, "name": name,
                    "ts": now_us() if ts_us is None else ts_us,
                    "s": "t", "args": args or {}})

    def window_begin(self, track: str, name: str, wid,
                     args: dict | None = None,
                     ts_us: int | None = None) -> None:
        """Opens an async slice (Trace Event ``b``) — fault windows and
        client invokes overlap freely, keyed by id instead of nesting."""
        if not self.enabled:
            return
        self._emit({"ph": "b", "track": track, "name": name,
                    "cat": "window", "id": str(wid),
                    "ts": now_us() if ts_us is None else ts_us,
                    "args": args or {}})

    def window_end(self, track: str, name: str, wid,
                   args: dict | None = None,
                   ts_us: int | None = None) -> None:
        if not self.enabled:
            return
        self._emit({"ph": "e", "track": track, "name": name,
                    "cat": "window", "id": str(wid),
                    "ts": now_us() if ts_us is None else ts_us,
                    "args": args or {}})

    @contextmanager
    def span(self, track: str, name: str, args: dict | None = None):
        """Scoped ``X`` slice: measures the block, emits once at exit."""
        if not self.enabled:
            yield self
            return
        t0 = now_us()
        try:
            yield self
        finally:
            self.complete(track, name, t0, now_us() - t0, args=args)

    # -- flight-recorder dumping -----------------------------------------

    def dump_flight(self, path, reason: str) -> bool:
        """Dumps the flight recorder's ring to ``path`` (jsonl, fsynced).
        Returns False when no recorder is attached or the dump failed;
        never raises — this runs on crash paths."""
        fl = self.flight
        if fl is None:
            return False
        ok = fl.dump(path, reason=reason)
        if ok:
            try:
                from jepsen_tpu import telemetry
                reg = telemetry.get_registry()
                if reg.enabled:
                    reg.counter(
                        "trace_flight_dumps_total",
                        "flight-recorder dumps, by trigger",
                        labels=("reason",)).inc(reason=reason)
            except Exception:  # noqa: BLE001 — a dump must never raise
                logger.exception("flight-dump telemetry failed")
        return ok

    def arm_crash_dump(self, path) -> None:
        """Registers an atexit hook that dumps the flight recorder if
        this tracer is never closed cleanly — the last line of defense
        when a run dies outside core.run's fatal-path dump."""
        import atexit
        with self._lock:
            self._crash_path = path
        atexit.register(self._atexit_dump)

    def _atexit_dump(self) -> None:
        with self._lock:
            if self._closed or self._crash_path is None:
                return
            path = self._crash_path
        self.dump_flight(path, reason="atexit")

    # -- lifecycle --------------------------------------------------------

    def close(self) -> None:
        """Flushes/terminates the sinks and disarms the crash hook.
        Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        import atexit
        try:
            atexit.unregister(self._atexit_dump)
        except Exception:  # noqa: BLE001
            pass
        if self.perfetto is not None:
            self.perfetto.close()


class NullTracer:
    """The disabled mode: every method a constant no-op."""

    enabled = False
    perfetto = None
    flight = None

    def begin(self, *a, **kw) -> None:
        pass

    def end(self, *a, **kw) -> None:
        pass

    def set_op_origin(self, origin_us: int) -> None:
        pass

    def op_sink(self):
        return None

    def complete(self, *a, **kw) -> None:
        pass

    def instant(self, *a, **kw) -> None:
        pass

    def window_begin(self, *a, **kw) -> None:
        pass

    def window_end(self, *a, **kw) -> None:
        pass

    @contextmanager
    def span(self, *a, **kw):
        yield self

    def dump_flight(self, path, reason: str) -> bool:
        return False

    def arm_crash_dump(self, path) -> None:
        pass

    def close(self) -> None:
        pass


NULL_TRACER = NullTracer()

_TRACER: RunTracer | NullTracer = NULL_TRACER
_TRACER_LOCK = threading.Lock()


def get_tracer() -> RunTracer | NullTracer:
    """The currently installed run tracer (NULL when tracing is off)."""
    return _TRACER


def install(tracer: RunTracer | NullTracer | None):
    """Swaps the process-global tracer; returns the previous one so
    callers can restore it (core.run does)."""
    global _TRACER
    with _TRACER_LOCK:
        prev = _TRACER
        _TRACER = tracer if tracer is not None else NULL_TRACER
        return prev


@contextmanager
def use(tracer: RunTracer | NullTracer):
    prev = install(tracer)
    try:
        yield tracer
    finally:
        install(prev)


# ---------------------------------------------------------------------------
# Knob coercion (KNB house style: tolerant at runtime, preflight errors)
# ---------------------------------------------------------------------------

def trace_enabled(test: dict | None) -> bool:
    """The ``trace`` knob, tolerantly: test map first, then the
    ``JEPSEN_TPU_TRACE`` env twin; garbage warns and reads as unset
    (``parallel.coerce_flag``, the house bool-knob coercer)."""
    from jepsen_tpu.parallel import coerce_flag
    v = coerce_flag((test or {}).get("trace"), knob="trace")
    if v is not None:
        return v
    env = coerce_flag(os.environ.get("JEPSEN_TPU_TRACE"),
                      knob="JEPSEN_TPU_TRACE")
    return bool(env)


def flight_recorder_events(test: dict | None) -> int:
    """The flight-recorder ring capacity: ``flight_recorder_events``
    in the test map, the ``JEPSEN_TPU_FLIGHT_RECORDER_EVENTS`` env
    twin, else :data:`DEFAULT_FLIGHT_EVENTS`. ``<= 0`` disables;
    garbage warns and takes the default."""
    for v, knob in (((test or {}).get("flight_recorder_events"),
                     "flight_recorder_events"),
                    (os.environ.get("JEPSEN_TPU_FLIGHT_RECORDER_EVENTS"),
                     "JEPSEN_TPU_FLIGHT_RECORDER_EVENTS")):
        if v is None or v == "":
            continue
        if isinstance(v, bool):
            logger.warning("unparsable %s=%r; using default %d", knob, v,
                           DEFAULT_FLIGHT_EVENTS)
            return DEFAULT_FLIGHT_EVENTS
        try:
            return max(0, int(float(v)))
        except (TypeError, ValueError):
            logger.warning("unparsable %s=%r; using default %d", knob, v,
                           DEFAULT_FLIGHT_EVENTS)
            return DEFAULT_FLIGHT_EVENTS
    return DEFAULT_FLIGHT_EVENTS


def for_test(test: dict) -> RunTracer | NullTracer:
    """Builds the run's tracer from its knobs: a Perfetto sink into the
    store dir at ``--trace`` verbosity, a flight recorder unless
    ``flight_recorder_events`` is 0. Returns NULL_TRACER when both are
    off (the default run's hot paths then pay one attribute read)."""
    perfetto = None
    if trace_enabled(test):
        try:
            from jepsen_tpu import store
            perfetto = PerfettoSink(store.path_mk(test, TRACE_NAME))
        except Exception:  # noqa: BLE001 — no store coords: no trace file
            logger.exception("couldn't open %s; span sink off", TRACE_NAME)
    capacity = flight_recorder_events(test)
    flight = FlightRecorder(capacity) if capacity > 0 else None
    if perfetto is None and flight is None:
        return NULL_TRACER
    return RunTracer(perfetto=perfetto, flight=flight)
