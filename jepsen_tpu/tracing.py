"""Lightweight op tracing: scoped spans with ids, annotations and
attributes, exported as JSONL (reference:
dgraph/src/jepsen/dgraph/trace.clj:1-73 — an opencensus wrapper whose
spans ship to a Jaeger endpoint; here the same span surface writes a
line-per-span log into the test's store directory, where the web UI and
offline tooling can read it without a tracing service).

Surface parity with the reference wrapper:

- :func:`with_trace`  — the ``with-trace`` scoped-span macro (a context
  manager; nested spans share the enclosing trace id)
- :func:`context`     — current {span-id, trace-id}
- :func:`annotate`    — timestamped annotation on the current span
- :func:`attribute`   — string k/v attributes on the current span
- :class:`TracedClient` — wraps any Client so each invoke runs in a
  span named after the op's ``f`` (how the dgraph suite's ``--trace``
  wires client ops, the with-trace call sites in dgraph/client.clj)

Spans are buffered per tracer and flushed by ``close()`` (or each
``max_buffer`` spans); a tracer with no path is a sampler that never
samples — every call is a no-op, the reference's neverSample mode.

Lifecycle: one tracer is typically SHARED by many TracedClients (every
``open()`` hands the same tracer to the per-node clone), so clients never
tear it down — the owner (core.run for ``--trace`` runs, or whoever
constructed it) calls ``close()``, which is idempotent. An ``atexit``
hook flushes whatever is still buffered so spans survive a crashed run.
"""
from __future__ import annotations

import atexit
import json
import os
import random
import threading
import time
from contextlib import contextmanager

from jepsen_tpu.client import Client

_local = threading.local()


def _trace_pkg():
    """The run-wide causal-trace package, lazily — tracing.py is the
    legacy per-client span log and must stay importable standalone."""
    from jepsen_tpu import trace as trace_mod
    return trace_mod


def _stack() -> list:
    s = getattr(_local, "spans", None)
    if s is None:
        s = _local.spans = []
    return s


class Tracer:
    """Collects spans; ``path=None`` disables sampling entirely.

    Span/trace ids come from a PER-TRACER seeded RNG (``seed``
    injectable for deterministic tests), never the global ``random``
    module: a tracer drawing from shared global state is exactly the
    stateful-closure shape preflight's GEN005 skip and the
    ``no-host-effects-in-jit`` rule assume away — and two tracers
    seeded identically must produce identical id streams regardless of
    what the rest of the process consumed."""

    def __init__(self, path: str | None, max_buffer: int = 512,
                 seed: int | None = None):
        self.path = path
        self.max_buffer = max_buffer
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._closed = False
        self._rng = random.Random(
            seed if seed is not None
            else int.from_bytes(os.urandom(8), "big"))
        if path is not None:
            # final-flush safety net: buffered spans survive a run that
            # crashes before the owner reaches close()
            atexit.register(self.close)

    def _new_id(self) -> str:
        # many TracedClients share one tracer, so draws race — the C
        # _random.Random keeps its state consistent under the GIL, and
        # a (vanishingly rare) duplicate id costs less than a lock on
        # every span
        return f"{self._rng.getrandbits(64):016x}"

    def enabled(self) -> bool:
        return self.path is not None

    @contextmanager
    def with_trace(self, name: str):
        """Scoped span: nested calls inherit the trace id and parent."""
        if not self.enabled():
            yield self
            return
        stack = _stack()
        parent = stack[-1] if stack else None
        span = {
            "name": name,
            "span-id": self._new_id(),
            "trace-id": parent["trace-id"] if parent else self._new_id(),
            "parent-id": parent["span-id"] if parent else None,
            "start": time.time(),
            "annotations": [],
            "attributes": {},
        }
        stack.append(span)
        try:
            yield self
        finally:
            stack.pop()
            span["end"] = time.time()
            self._emit(span)

    def context(self) -> dict:
        """{span-id, trace-id} of the current span (trace.clj context)."""
        stack = _stack()
        if not stack:
            return {"span-id": None, "trace-id": None}
        return {"span-id": stack[-1]["span-id"],
                "trace-id": stack[-1]["trace-id"]}

    def annotate(self, message: str) -> None:
        stack = _stack()
        if stack:
            stack[-1]["annotations"].append(
                {"t": time.time(), "message": str(message)})

    def attribute(self, k, v=None) -> None:
        """One pair or a map of pairs; values stringified (the
        reference's all-strings opencensus constraint, kept for log
        stability)."""
        stack = _stack()
        if not stack:
            return
        attrs = {k: v} if not isinstance(k, dict) else k
        stack[-1]["attributes"].update(
            {str(kk): str(vv) for kk, vv in attrs.items()})

    def _emit(self, span: dict) -> None:
        with self._lock:
            self._buf.append(span)
            if len(self._buf) >= self.max_buffer:
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf or not self.path:
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as f:
            for span in self._buf:
                f.write(json.dumps(span, default=str) + "\n")
        self._buf.clear()

    def flush(self) -> None:
        """Writes any buffered spans; safe to call at any time."""
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        """Flushes and unhooks the atexit net. Idempotent — a shared
        tracer may be closed by its owner AND the atexit hook."""
        self.flush()
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.path is not None:
            try:
                atexit.unregister(self.close)
            except Exception:  # noqa: BLE001
                pass


class TracedClient(Client):
    """Wraps a client so every invoke runs inside a span named after the
    op's f, attributed with node/process/type (the dgraph with-trace
    call-site pattern)."""

    def __init__(self, inner: Client, tracer: Tracer,
                 node: str | None = None):
        self.inner = inner
        self.tracer = tracer
        self.node = node

    @property
    def reusable(self):  # delegate reuse semantics
        return getattr(self.inner, "reusable", False)

    def open(self, test, node):
        fresh = self.inner.open(test, node)
        # symmetric peeling (the _unwrap_client contract, in reverse):
        # a suite whose open() hands back an ALREADY-traced client —
        # e.g. one that routes through the test map's wrapped prototype
        # — must not double-wrap (nested spans per op) and must not
        # swap tracers; exactly ONE layer, OUR tracer, survives a
        # reopen (regression-pinned by the two-open test)
        while isinstance(fresh, TracedClient):
            fresh = fresh.inner
        return TracedClient(fresh, self.tracer, node)

    def setup(self, test):
        self.inner.setup(test)

    def invoke(self, test, op):
        with self.tracer.with_trace(f"invoke/{op.get('f')}"):
            self.tracer.attribute({"node": self.node,
                                   "process": op.get("process")})
            tm = _trace_pkg()
            if tm.get_tracer().enabled:
                # the run-wide causal id rides the client span as an
                # attribute: the same (process, invoke-time) id the
                # interpreter's dispatch slice carries, so trace.jsonl
                # client spans join trace.json worker slices exactly
                # (doc/observability.md "Causal trace")
                self.tracer.attribute(
                    "trace-id", tm.trace_id_for(op.get("process"),
                                                op.get("time")))
            out = self.inner.invoke(test, op)
            self.tracer.attribute("type", out.get("type"))
            if out.get("error") is not None:
                self.tracer.attribute("error", out.get("error"))
            return out

    def teardown(self, test):
        self.inner.teardown(test)

    def close(self, test):
        # flush but do NOT close: the tracer is shared with every other
        # TracedClient opened from the same prototype — teardown belongs
        # to the owner (core.run / the suite that built it)
        self.inner.close(test)
        self.tracer.flush()
