"""Write-ahead history journal: crash-safe op persistence.

The reference holds the whole history in memory until ``store/save-1!``
(core.clj:395) — a control-process crash mid-run discards every recorded
op. Here the generator interpreter's single-writer scheduler thread
appends each history-bound op (invocations at dispatch, completions as
they arrive) to ``store/<test>/<ts>/history.wal.jsonl`` as it happens,
so a SIGKILLed run leaves a replayable prefix of the history behind.

Durability knobs (test map):

* ``wal: False`` — disable journaling entirely.
* ``wal_fsync_interval`` — seconds between fsyncs (default
  :data:`DEFAULT_FSYNC_INTERVAL_S`). ``0`` fsyncs every append
  (power-loss safe, slow); a negative value never fsyncs (the flush
  per append still makes every op SIGKILL-safe — kernel page cache
  survives process death, not power loss).

The reader side (:func:`read_wal` / :func:`read_jsonl_tolerant`)
tolerates a torn final line: a crash can land mid-``write`` and leave a
partial JSON document on the last line, which is dropped rather than
raising ``json.JSONDecodeError``. ``cli analyze --recover`` rebuilds a
checkable history from the journal of a crashed run
(doc/robustness.md).
``ENOSPC`` is the one write failure treated as transient rather than
fatal: a full disk usually drains (log rotation, a neighbour's cleanup,
an operator), so the journal **parks** the failed lines in a bounded
in-memory buffer and retries them on the next append instead of
permanently self-disabling the way a generically dying disk does
(doc/robustness.md "Fleet HA").
"""
from __future__ import annotations

import errno
import hashlib
import json
import logging
import os
import threading
import time
from pathlib import Path

logger = logging.getLogger("jepsen.journal")

WAL_NAME = "history.wal.jsonl"
LATE_NAME = "late.jsonl"
DEFAULT_FSYNC_INTERVAL_S = 1.0
# lines held in memory while the disk is full; older lines drop first
# once exceeded (counted in Journal.parked_dropped) — bounding memory
# matters more than completeness once ENOSPC persists
ENOSPC_PARK_MAX_LINES = 10_000


class Journal:  # durability: fsync
    """Append-only jsonl journal with interval fsync.

    ``append`` is called from the interpreter's scheduler thread only;
    the lock exists so an abnormal-shutdown ``close`` from the
    orchestrator thread can't race a final append."""

    def __init__(self, path, fsync_interval_s: float = DEFAULT_FSYNC_INTERVAL_S):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        # binary mode: byte-exact offsets make the ENOSPC rollback in
        # _write_locked possible (truncate back to the last good line
        # boundary, so a retried park can't duplicate bytes a failed
        # flush partially landed)
        self._f = open(self.path, "wb")
        self.fsync_interval_s = fsync_interval_s
        self._last_fsync = time.monotonic()
        self._lock = threading.Lock()
        self.appended = 0
        # ENOSPC park state: lines waiting for the disk to drain, the
        # byte offset of the last fully-flushed line boundary, and
        # whether the tail may still hold a partial line (only when the
        # rollback truncate itself failed — terminated with a bare
        # newline on resume; the tolerant readers skip torn lines)
        self.parked: list[bytes] = []
        self.parked_dropped = 0
        self._good_offset = 0
        self._parked_closed = False
        self._dirty_tail = False
        self._park_logged = False

    def _park(self, parts: list[bytes]) -> None:
        """Holds lines in the bounded in-memory buffer while the disk
        is full; oldest lines drop first past the cap."""
        keep = self.parked + parts
        overflow = len(keep) - ENOSPC_PARK_MAX_LINES
        if overflow > 0:
            self.parked_dropped += overflow
            keep = keep[overflow:]
        self.parked = keep
        if not self._park_logged:
            self._park_logged = True
            logger.warning(
                "WAL %s hit ENOSPC; parking lines in memory (bounded "
                "at %d) until the disk drains", self.path,
                ENOSPC_PARK_MAX_LINES)

    def _write_locked(self, parts: list[bytes]) -> bool:
        """Writes ``parts`` — plus any ENOSPC-parked backlog — under
        the caller's lock. Returns True on success; False when the disk
        is (still) full and the lines were parked for the next append;
        re-raises any other OSError for the caller's permanent-disable
        path."""
        if self._f.closed:
            # the previous ENOSPC dropped the handle (with its
            # un-flushable buffer); reopen at the rolled-back tail
            try:
                self._f = open(self.path, "ab")
            except OSError as e:
                if e.errno != errno.ENOSPC:
                    raise
                self._park(parts)
                return False
            self._parked_closed = False
        pending = self.parked + parts
        if self._dirty_tail:
            # rollback couldn't truncate the partial line a failed
            # flush landed: a bare newline terminates it into a torn
            # line the tolerant readers already skip, instead of
            # gluing the retry onto it
            pending = [b"\n"] + pending
        try:
            # fsync rides the interval in _fsync_locked, invoked by the
            # append/append_many callers right after a successful write
            self._f.write(b"".join(pending))  # lint: ignore[fsync-pairing]
            self._f.flush()
        except OSError as e:
            if e.errno != errno.ENOSPC:
                raise
            # disk full is transient in a way a dying disk isn't. Drop
            # the handle — close() discards the un-flushable buffer so
            # a retry can't double-write it — and roll the OS file back
            # to the last good line boundary so partially-landed bytes
            # can't duplicate either; then park the batch for the next
            # append.
            try:
                self._f.close()
            except OSError:
                pass
            self._parked_closed = True
            try:
                if self.path.stat().st_size > self._good_offset:
                    os.truncate(self.path, self._good_offset)
                self._dirty_tail = False
            except OSError:
                self._dirty_tail = True
            self._park(parts)
            return False
        self.appended += len(self.parked) + len(parts)
        self._good_offset += sum(len(p) for p in pending)
        self.parked = []
        self._dirty_tail = False
        if self._park_logged:
            self._park_logged = False
            logger.info("WAL %s recovered from ENOSPC; parked lines "
                        "flushed (%d dropped while full)", self.path,
                        self.parked_dropped)
        return True

    def _fsync_locked(self) -> None:
        """Interval fsync under the caller's lock (the durability
        boundary — everything before this instant survives power
        loss)."""
        interval = self.fsync_interval_s
        if interval is None or interval < 0:
            return
        now = time.monotonic()
        if interval == 0 or now - self._last_fsync >= interval:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._last_fsync = now
            # causal trace: the durability boundary is an event worth
            # seeing next to the op slices (per-append emission would
            # double the hot path; the op itself is already traceable
            # via its derivable trace id)
            from jepsen_tpu import trace as trace_mod
            tracer = trace_mod.get_tracer()
            if tracer.enabled:
                tracer.instant(trace_mod.TRACK_WAL, "wal-fsync",
                               args={"appended": self.appended})

    def append(self, op: dict) -> None:
        """Writes one op as a JSON line, flushed to the OS immediately
        (SIGKILL-safe) and fsynced on the configured interval
        (power-loss-safe). Failures — unserializable op, disk full —
        are logged, never raised: the journal must not take down the
        run it protects. ``ENOSPC`` parks the line for a retry on the
        next append; any other OSError closes the journal and the run
        continues with the in-memory history, exactly the pre-WAL
        behavior."""
        from jepsen_tpu.store import _serializable
        try:
            line = (json.dumps(_serializable(op)) + "\n").encode("utf-8")
        except Exception:  # noqa: BLE001 — journaling never kills a run
            logger.exception("unserializable op dropped from WAL")
            return
        with self._lock:
            if self._f.closed and not self._parked_closed:
                return
            try:
                if self._write_locked([line]):
                    self._fsync_locked()
            except OSError:
                logger.exception("WAL write failed; journaling off for "
                                 "the rest of the run")
                try:
                    self._f.close()
                except OSError:
                    pass

    # owner: interpreter scheduler thread (sole writer); the lock only
    # guards against an abnormal-shutdown close() from the orchestrator
    def append_many(self, ops) -> None:
        """Batched twin of :meth:`append` for the chunked scheduler
        drain (doc/performance.md "Host ingest spine"): serializes the
        whole batch, then does ONE write+flush — and at most one
        interval fsync — instead of a syscall pair per op. An
        unserializable op drops that op only, exactly as in
        :meth:`append`; the surviving lines still land in batch order,
        so the WAL bytes are identical to per-op appends of the same
        sequence."""
        from jepsen_tpu.store import _serializable
        parts: list[bytes] = []
        for op in ops:
            try:
                parts.append(
                    (json.dumps(_serializable(op)) + "\n").encode("utf-8"))
            except Exception:  # noqa: BLE001 — journaling never kills a run
                logger.exception("unserializable op dropped from WAL")
        if not parts:
            return
        with self._lock:
            if self._f.closed and not self._parked_closed:
                return
            try:
                if self._write_locked(parts):
                    self._fsync_locked()
            except OSError:
                logger.exception("WAL write failed; journaling off for "
                                 "the rest of the run")
                try:
                    self._f.close()
                except OSError:
                    pass

    def sync(self) -> None:
        with self._lock:
            if self._f.closed and not self._parked_closed:
                return
            if self.parked or self._parked_closed:
                try:
                    if not self._write_locked([]):
                        return  # still full: nothing new to make durable
                except OSError:
                    logger.exception("WAL sync flush failed")
                    return
            self._f.flush()
            os.fsync(self._f.fileno())
            self._last_fsync = time.monotonic()

    def close(self, discard: bool = False) -> None:
        """Flushes and closes; ``discard=True`` additionally unlinks the
        file — core.run discards the WAL once ``store.save_1`` has
        persisted the authoritative ``history.jsonl`` (a surviving WAL
        without a history.jsonl next to it marks a crashed run)."""
        with self._lock:
            if not self._f.closed or self._parked_closed:
                try:
                    if self.parked or self._parked_closed:
                        self._write_locked([])  # last ENOSPC-drain try
                    if not self._f.closed:
                        self._f.flush()
                        os.fsync(self._f.fileno())
                except OSError:
                    logger.exception("WAL final fsync failed")
                try:
                    self._f.close()
                except OSError:
                    pass
                self._parked_closed = False
        if discard:
            try:
                self.path.unlink(missing_ok=True)
            except OSError:
                logger.exception("couldn't discard WAL %s", self.path)


class ForensicLog:
    """Lazily-opened append-only jsonl for forensic artifacts — the
    quarantined-late-completion log (``late.jsonl``) the interpreter's
    deadline layer writes when a reaped zombie worker finally returns
    (doc/robustness.md). Same never-raise contract as :class:`Journal`:
    a forensic artifact must not take down the run it documents. The
    file is only created on first append, so clean runs leave no empty
    artifacts behind."""

    def __init__(self, path):
        self.path = Path(path)
        self._f = None
        self._broken = False
        self._lock = threading.Lock()
        self.appended = 0

    def append(self, row: dict) -> None:
        from jepsen_tpu.store import _serializable
        try:
            line = json.dumps(_serializable(row)) + "\n"
        except Exception:  # noqa: BLE001 — forensics never kill a run
            logger.exception("unserializable row dropped from %s",
                             self.path.name)
            return
        with self._lock:
            if self._broken:
                return
            try:
                if self._f is None:
                    self.path.parent.mkdir(parents=True, exist_ok=True)
                    self._f = open(self.path, "a", encoding="utf-8")
                self._f.write(line)
                self._f.flush()
                self.appended += 1
            except OSError:
                logger.exception("forensic log %s failed; disabled",
                                 self.path)
                self._broken = True
                if self._f is not None:
                    try:
                        self._f.close()
                    except OSError:
                        pass

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                try:
                    self._f.close()
                except OSError:
                    logger.exception("forensic log close failed")


def read_jsonl_tolerant(path) -> tuple[list[dict], bool]:
    """Parses a jsonl file, tolerating the torn final line a crash (or a
    file-truncate nemesis aimed at ourselves) leaves behind. Returns
    ``(rows, truncated)`` — ``truncated`` is True when a final partial
    line was dropped. A malformed *interior* line — a crash during
    interleaved writers, a disk hiccup — is logged and skipped WITHOUT
    discarding the valid lines after it: one tear costs one op, never
    the rest of the journal (regression-pinned in tests/test_live.py)."""
    rows: list[dict] = []
    truncated = False
    with open(path, encoding="utf-8", errors="replace") as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1 and not line.endswith("\n"):
                truncated = True
                logger.debug("dropped torn final jsonl line in %s", path)
            else:
                logger.warning("skipping malformed jsonl line %d in %s",
                               i + 1, path)
    # a last line without its newline parsed fine only if the tear
    # happened to land on a document boundary; count it as complete
    return rows, truncated


def parse_wal_chunk_py(chunk: bytes, final: bool = False):
    """Pure-Python twin of the native ``ingest_chunk`` scanner — the
    WAL chunk protocol both paths implement bit-identically
    (doc/performance.md "Host ingest spine").

    Takes the raw bytes read from a WAL at some resume cursor and
    returns ``(ops, consumed, torn, truncated)``:

    * ``ops`` — the parsed documents of every complete (newline-
      terminated) line, in order; whitespace-only lines skipped.
    * ``consumed`` — bytes the caller's cursor may advance past: the
      newline-terminated prefix, plus the dropped unterminated tail
      when ``final``. Never lands mid-line, so ``(offset, prefix_sha)``
      stays a valid resume token at every chunk boundary.
    * ``torn`` — newline-terminated lines that didn't parse (interior
      tears), plus the dropped tail when ``final`` truncates one.
    * ``truncated`` — True when ``final`` dropped an unterminated
      in-progress final line.
    """
    ops: list = []
    torn = 0
    nl = chunk.rfind(b"\n")
    pos = nl + 1  # bytes of newline-terminated (complete) lines
    loads = json.loads
    if pos:
        # fast path: the whole complete portion as ONE json array
        # (~2.7x a per-line loop); tolerant per-line path only when
        # something in the chunk doesn't parse
        body = chunk[:nl]
        text = None
        try:
            # strict decode BEFORE the one-array parse: json.loads on
            # raw bytes decodes with surrogatepass, so a chunk of
            # all-valid lines would keep raw lone-surrogate bytes as
            # surrogates while the same line next to a torn neighbor
            # (or read through WalTailer/read_jsonl_tolerant) gets
            # U+FFFD replacement — parse results must not depend on
            # neighboring lines (found by fuzz-native, exec seed 0:271).
            # Join with ",\n", NOT ",": a torn line with an unbalanced
            # quote would otherwise swallow bare-comma separators into
            # its string literal and weld neighboring lines into one
            # bogus document; keeping the newline makes that a raw
            # control char inside a string, which strict JSON rejects
            # (seed 0:2712)
            text = body.decode("utf-8")
            ops = loads("[" + text.replace("\n", ",\n") + "]")
            # the fast path is only trustworthy when every line maps to
            # exactly ONE array element. Torn lines can weld through a
            # *structural* position — ",\n" between two halves of a
            # split numeric array is legal JSON whitespace, so
            # "[...,1" + "37,...]" parses as one bogus document (seed
            # 0:90681) — and a single line holding two documents
            # ("{...},{...}", a mid-line splice) parses as two elements
            # where the per-line contract says one torn line. Either
            # direction changes the element count, so a count mismatch
            # drops to the tolerant per-line path.
            fast_ok = len(ops) == text.count("\n") + 1
        except (json.JSONDecodeError, UnicodeDecodeError):
            fast_ok = False
        if not fast_ok:
            ops = []
            if text is None:
                text = body.decode("utf-8", "replace")
            for line in text.split("\n"):
                if not line or line.isspace():
                    continue
                try:
                    ops.append(loads(line))
                except json.JSONDecodeError:
                    torn += 1
                    logger.debug("torn jsonl line in chunk (%.80r)", line)
    consumed = pos
    truncated = False
    if final and pos < len(chunk):
        # unterminated tail at end-of-run: permanently torn
        truncated = True
        torn += 1
        consumed = len(chunk)
    return ops, consumed, torn, truncated


class WalTailer:
    """Incremental offset-tracking WAL reader for the live checker
    (doc/observability.md "Live checking").

    ``poll()`` returns the ops appended since the last poll. The tailer
    remembers the byte offset of the last fully-parsed line, so each
    poll reads only the new tail:

    * an **in-progress final line** (no trailing newline yet — the
      writer is mid-``write``) is left unread; the offset does not
      advance past it, so the next poll resumes at its start and picks
      it up once the writer finishes the line;
    * a **newline-terminated line that doesn't parse** (a torn line
      *mid-file*: crash during interleaved writers, disk damage) is
      logged, counted in ``torn_skipped``, and skipped — the valid
      lines after it are still delivered;
    * ``finalize()`` drains everything and additionally drops a
      still-unterminated final partial line (the run is over; nobody
      will complete it), setting ``truncated_tail``.

    A missing file reads as zero new ops (the run may not have opened
    its journal yet, or `core.run` already discarded it after save_1 —
    the tracker falls over to history.jsonl in that case)."""

    def __init__(self, path):
        self.path = Path(path)
        self.offset = 0
        self.lines_read = 0
        self.torn_skipped = 0
        self.truncated_tail = False
        # running digest of every byte the offset has advanced past —
        # the live daemon's restart snapshots record it so a resumed
        # tailer can prove it is continuing the SAME file (divergence-
        # checked adoption, doc/robustness.md "Resumable checks and the
        # elastic mesh"). Maintained LAZILY: hashing 30-60ns/op on the
        # ingest hot loop for a digest that is only read at snapshot
        # points would cost real throughput, and the consumed prefix of
        # an append-only WAL never changes — so poll() just advances
        # the offset and prefix_sha() catches the digest up from the
        # file on demand.
        self._sha = hashlib.sha256()
        self._sha_pos = 0  # bytes already folded into _sha

    def prefix_sha(self) -> str:
        """sha256 of the bytes consumed so far (everything before
        ``offset``)."""
        if self._sha_pos < self.offset:
            try:
                with open(self.path, "rb") as f:
                    f.seek(self._sha_pos)
                    remaining = self.offset - self._sha_pos
                    while remaining > 0:
                        chunk = f.read(min(1 << 20, remaining))
                        if not chunk:
                            break  # truncated under us; digest of what
                        self._sha.update(chunk)
                        self._sha_pos += len(chunk)
                        remaining -= len(chunk)
            except OSError:
                pass
        return self._sha.hexdigest()

    def seek(self, offset: int, lines_read: int = 0,
             torn_skipped: int = 0, prefix_sha: str | None = None) -> bool:
        """Repositions a FRESH tailer at a snapshot's offset — the
        restart path. Verifies the snapshot's ``prefix_sha`` against
        the file's actual first ``offset`` bytes before adopting;
        a mismatch (truncated/rewritten WAL, a different run reusing
        the dir) returns False and leaves the tailer at 0, so the
        caller re-ingests from scratch instead of trusting a stale
        cursor."""
        offset = int(offset)
        h = hashlib.sha256()
        try:
            with open(self.path, "rb") as f:
                remaining = offset
                while remaining > 0:
                    chunk = f.read(min(1 << 20, remaining))
                    if not chunk:
                        return False  # file shorter than the snapshot
                    h.update(chunk)
                    remaining -= len(chunk)
        except OSError:
            return False
        if prefix_sha is not None and h.hexdigest() != prefix_sha:
            return False
        self.offset = offset
        self.lines_read = int(lines_read)
        self.torn_skipped = int(torn_skipped)
        self._sha = h
        self._sha_pos = offset
        return True

    def _read_new(self) -> bytes:
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                return f.read()
        except OSError:
            return b""

    def poll(self, final: bool = False) -> list[dict]:
        chunk = self._read_new()
        if not chunk:
            return []
        # the hot loop lives in the native ingest spine when available
        # (native/columnar_ext.c ingest_chunk, ~10x json.loads on op
        # traffic); parse_wal_chunk_py is the bit-identical fallback
        # behind the probe/disable protocol (doc/performance.md)
        from jepsen_tpu.history_ir import ingest
        ops, consumed, torn, truncated = ingest.parse_wal_chunk(
            chunk, final=final)
        self.lines_read += len(ops)
        if torn:
            self.torn_skipped += torn
            interior = torn - (1 if truncated else 0)
            if interior:
                logger.warning("live tail: skipped %d torn jsonl "
                               "line(s) in %s", interior, self.path)
        # the offset only ever advances past newline-terminated lines
        # (plus the dropped tail when final); the prefix digest catches
        # up lazily from the file (seek() verifies it)
        self.offset += consumed
        if truncated:
            self.truncated_tail = True
            logger.warning("live tail: dropped unterminated final line "
                           "in %s", self.path)
        return ops

    def poll_bytes(self) -> bytes:
        """Raw shipping twin of :meth:`poll`: the newline-terminated
        bytes appended since the last poll, advancing ``offset`` /
        ``lines_read`` / the prefix digest in lockstep — WITHOUT
        parsing. The fleet ingest plane ships these bytes verbatim, so
        the receiver's file is a byte-identical prefix of the source
        WAL and its checker verdicts match the local path bit for bit
        (doc/observability.md "Fleet plane").

        The torn-boundary contract is inherited: an in-progress final
        line (no trailing newline yet) is left unread, so a shipped
        chunk never ends mid-document and ``(offset, prefix_sha())``
        stays a valid resume token at every chunk boundary."""
        chunk = self._read_new()
        if not chunk:
            return b""
        nl = chunk.rfind(b"\n")
        if nl < 0:
            return b""  # only an in-progress line so far: ship nothing
        body = chunk[:nl + 1]
        self.lines_read += body.count(b"\n")
        self.offset += len(body)
        return body

    def finalize(self) -> list[dict]:
        return self.poll(final=True)


def read_wal(path) -> tuple[list[dict], bool]:
    """The ops recovered from a journal, plus the torn-tail flag."""
    return read_jsonl_tolerant(path)


def wal_path(test: dict):
    from jepsen_tpu import store
    return store.path(test, WAL_NAME)


def late_path(test: dict):
    from jepsen_tpu import store
    return store.path(test, LATE_NAME)
