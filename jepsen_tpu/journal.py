"""Write-ahead history journal: crash-safe op persistence.

The reference holds the whole history in memory until ``store/save-1!``
(core.clj:395) — a control-process crash mid-run discards every recorded
op. Here the generator interpreter's single-writer scheduler thread
appends each history-bound op (invocations at dispatch, completions as
they arrive) to ``store/<test>/<ts>/history.wal.jsonl`` as it happens,
so a SIGKILLed run leaves a replayable prefix of the history behind.

Durability knobs (test map):

* ``wal: False`` — disable journaling entirely.
* ``wal_fsync_interval`` — seconds between fsyncs (default
  :data:`DEFAULT_FSYNC_INTERVAL_S`). ``0`` fsyncs every append
  (power-loss safe, slow); a negative value never fsyncs (the flush
  per append still makes every op SIGKILL-safe — kernel page cache
  survives process death, not power loss).

The reader side (:func:`read_wal` / :func:`read_jsonl_tolerant`)
tolerates a torn final line: a crash can land mid-``write`` and leave a
partial JSON document on the last line, which is dropped rather than
raising ``json.JSONDecodeError``. ``cli analyze --recover`` rebuilds a
checkable history from the journal of a crashed run
(doc/robustness.md).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path

logger = logging.getLogger("jepsen.journal")

WAL_NAME = "history.wal.jsonl"
LATE_NAME = "late.jsonl"
DEFAULT_FSYNC_INTERVAL_S = 1.0


class Journal:  # durability: fsync
    """Append-only jsonl journal with interval fsync.

    ``append`` is called from the interpreter's scheduler thread only;
    the lock exists so an abnormal-shutdown ``close`` from the
    orchestrator thread can't race a final append."""

    def __init__(self, path, fsync_interval_s: float = DEFAULT_FSYNC_INTERVAL_S):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "w", encoding="utf-8")
        self.fsync_interval_s = fsync_interval_s
        self._last_fsync = time.monotonic()
        self._lock = threading.Lock()
        self.appended = 0

    def append(self, op: dict) -> None:
        """Writes one op as a JSON line, flushed to the OS immediately
        (SIGKILL-safe) and fsynced on the configured interval
        (power-loss-safe). Failures — unserializable op, disk full —
        are logged, never raised: the journal must not take down the
        run it protects. A dying WAL (OSError) closes itself; the run
        continues with the in-memory history, exactly the pre-WAL
        behavior."""
        from jepsen_tpu.store import _serializable
        try:
            line = json.dumps(_serializable(op)) + "\n"
        except Exception:  # noqa: BLE001 — journaling never kills a run
            logger.exception("unserializable op dropped from WAL")
            return
        with self._lock:
            if self._f.closed:
                return
            try:
                self._f.write(line)
                self._f.flush()
                self.appended += 1
                interval = self.fsync_interval_s
                if interval is not None and interval >= 0:
                    now = time.monotonic()
                    if interval == 0 or now - self._last_fsync >= interval:
                        os.fsync(self._f.fileno())
                        self._last_fsync = now
            except OSError:
                logger.exception("WAL write failed; journaling off for "
                                 "the rest of the run")
                try:
                    self._f.close()
                except OSError:
                    pass

    def sync(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._last_fsync = time.monotonic()

    def close(self, discard: bool = False) -> None:
        """Flushes and closes; ``discard=True`` additionally unlinks the
        file — core.run discards the WAL once ``store.save_1`` has
        persisted the authoritative ``history.jsonl`` (a surviving WAL
        without a history.jsonl next to it marks a crashed run)."""
        with self._lock:
            if not self._f.closed:
                try:
                    self._f.flush()
                    os.fsync(self._f.fileno())
                except OSError:
                    logger.exception("WAL final fsync failed")
                self._f.close()
        if discard:
            try:
                self.path.unlink(missing_ok=True)
            except OSError:
                logger.exception("couldn't discard WAL %s", self.path)


class ForensicLog:
    """Lazily-opened append-only jsonl for forensic artifacts — the
    quarantined-late-completion log (``late.jsonl``) the interpreter's
    deadline layer writes when a reaped zombie worker finally returns
    (doc/robustness.md). Same never-raise contract as :class:`Journal`:
    a forensic artifact must not take down the run it documents. The
    file is only created on first append, so clean runs leave no empty
    artifacts behind."""

    def __init__(self, path):
        self.path = Path(path)
        self._f = None
        self._broken = False
        self._lock = threading.Lock()
        self.appended = 0

    def append(self, row: dict) -> None:
        from jepsen_tpu.store import _serializable
        try:
            line = json.dumps(_serializable(row)) + "\n"
        except Exception:  # noqa: BLE001 — forensics never kill a run
            logger.exception("unserializable row dropped from %s",
                             self.path.name)
            return
        with self._lock:
            if self._broken:
                return
            try:
                if self._f is None:
                    self.path.parent.mkdir(parents=True, exist_ok=True)
                    self._f = open(self.path, "a", encoding="utf-8")
                self._f.write(line)
                self._f.flush()
                self.appended += 1
            except OSError:
                logger.exception("forensic log %s failed; disabled",
                                 self.path)
                self._broken = True
                if self._f is not None:
                    try:
                        self._f.close()
                    except OSError:
                        pass

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                try:
                    self._f.close()
                except OSError:
                    logger.exception("forensic log close failed")


def read_jsonl_tolerant(path) -> tuple[list[dict], bool]:
    """Parses a jsonl file, tolerating the torn final line a crash (or a
    file-truncate nemesis aimed at ourselves) leaves behind. Returns
    ``(rows, truncated)`` — ``truncated`` is True when a final partial
    line was dropped. A malformed *interior* line is skipped with a
    warning (defensive: interior tears can't happen from our writer, but
    a recovery tool must not die on one)."""
    rows: list[dict] = []
    truncated = False
    with open(path, encoding="utf-8", errors="replace") as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                truncated = True
                logger.debug("dropped torn final jsonl line in %s", path)
            else:
                logger.warning("skipping malformed jsonl line %d in %s",
                               i + 1, path)
    # a last line without its newline parsed fine only if the tear
    # happened to land on a document boundary; count it as complete
    return rows, truncated


def read_wal(path) -> tuple[list[dict], bool]:
    """The ops recovered from a journal, plus the torn-tail flag."""
    return read_jsonl_tolerant(path)


def wal_path(test: dict):
    from jepsen_tpu import store
    return store.path(test, WAL_NAME)


def late_path(test: dict):
    from jepsen_tpu import store
    return store.path(test, LATE_NAME)
