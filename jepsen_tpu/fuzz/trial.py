"""One schedule → one deterministic fake-mode run.

A trial is a PURE function of its :class:`~jepsen_tpu.fuzz.schedule.
Schedule`: the client ops come from a seeded generator through
:func:`jepsen_tpu.generator.simulate.simulate` (wall cap on a
:class:`~jepsen_tpu.generator.simulate.StepClock`, so load can't skew
truncation), the fault model draws every coin from the schedule's own
rng, and the register semantics under each fault window are fixed.
Same schedule ⇒ byte-identical history — the replay contract
(doc/robustness.md "Schedule fuzzing") rests on this.

Fault semantics on the fake register target:

* ``net`` (partition) — ops invoked inside the window complete as
  ``:info`` (indeterminate); whether the effect applied is a seeded
  coin. Exactly the pressure that grows the checker frontier.
* ``clock-rate`` — completion latency scales by the faketime rate
  factor (fast clock, short window); composes with membership via
  ``FakeClusterState.set_clock_rate``.
* ``pause`` — SIGSTOP-ish: completion latency stretches 5×, so ops
  overlap that otherwise wouldn't.
* ``membership`` — a one-shot grow/shrink through a real
  :class:`~jepsen_tpu.fakes.FakeClusterState` (durable members file,
  settle window on the cluster clock).

``PlantedBug`` is the seam tests use to hide an anomaly behind a
specific fault×op interleaving: a staged state machine that arms on
(fault-mask, f) matches and, fully armed, tears one write — acked
``ok`` but leaving the register corrupted — so the next read returns
a value nobody ever wrote, which no linearization explains.
"""
from __future__ import annotations

import json
import random
from pathlib import Path

from jepsen_tpu import generator as gen_mod
from jepsen_tpu.fuzz.schedule import WINDOW_OPS, Schedule
from jepsen_tpu.generator.simulate import StepClock, simulate
from jepsen_tpu.journal import WAL_NAME, Journal
from jepsen_tpu.utils import ms_to_nanos

N_VALUES = 5

# cap on indeterminate (:info) completions per trial: each one leaves
# a forever-open slot in the checker frontier, and an 80-op partition
# window of pure timeouts is a 2^31-config search (a real partitioned
# client times out a few in-flight ops, then fails fast on connection
# refused — determinate :fail, which the encoder drops entirely)
MAX_CRASHES = 6


class PlantedBug:
    """Interleaving-gated torn-write fault: ``stages`` is a list of
    ``(kinds, f)`` pairs; a completed op whose active fault-kind set
    covers ``kinds`` and whose ``:f`` matches arms the next stage. The
    FINAL stage's matching op is acknowledged ``ok`` with
    its effect torn (the register left corrupted) — then re-arms from
    zero. Serializable via ``spec`` for replay."""

    def __init__(self, stages):
        self.stages = [(frozenset(kinds), str(f)) for kinds, f in stages]
        self.armed = 0

    @classmethod
    def from_spec(cls, spec) -> "PlantedBug | None":
        if not spec:
            return None
        return cls([(tuple(kinds), f) for kinds, f in spec])

    def spec(self) -> list:
        return [[sorted(kinds), f] for kinds, f in self.stages]

    def on_op(self, f: str, active: frozenset) -> bool:
        """True when this op's effect must be dropped (acked ok)."""
        if not self.stages:
            return False
        kinds, want_f = self.stages[self.armed]
        if f == want_f and kinds <= active:
            self.armed += 1
            if self.armed == len(self.stages):
                self.armed = 0
                return True
        return False


def run_trial(schedule: Schedule, bug: PlantedBug | None = None
              ) -> list[dict]:
    """The schedule's history: client invokes/completions from the
    simulator interleaved with the nemesis ``:info`` ops that delimit
    its fault windows (so :func:`jepsen_tpu.nemesis.faults.
    history_windows`-style consumers see real windows)."""
    rng = random.Random(schedule.seed)
    op_rng = random.Random(rng.getrandbits(64))
    fault_rng = random.Random(rng.getrandbits(64))

    wins = schedule.windows_ops()

    def active_at(i: int) -> frozenset:
        return frozenset(kind for (s, e, kind) in wins if s <= i < e)

    # membership rides a real FakeClusterState on a virtual clock —
    # deterministic, durable, honoring the satellite-2 settle contract
    cluster = None
    member_wins = [w for w in wins if w[2] == "membership"]
    if member_wins:
        import tempfile

        from jepsen_tpu.fakes import FakeClusterState
        vclock = {"t": 0.0}
        tmp = tempfile.mkdtemp(prefix="jepsen-fuzz-members-")
        cluster = FakeClusterState(
            Path(tmp) / "members.json",
            nodes=[f"n{i}" for i in range(1, 6)],
            settle_s=float(schedule.knobs.get("settle_s", 0.0)),
            min_members=int(schedule.knobs.get("min_members", 1)),
            time_fn=lambda: vclock["t"])

    def mk_gen():
        def f():
            roll = op_rng.random()
            if roll < 0.4:
                return {"f": "read", "value": None}
            if roll < 0.8:
                return {"f": "write",
                        "value": op_rng.randrange(N_VALUES)}
            return {"f": "cas", "value": [op_rng.randrange(N_VALUES),
                                          op_rng.randrange(N_VALUES)]}
        # clients(): the sim context carries a nemesis thread, and an
        # op dispatched there would mutate the register invisibly (the
        # encoder drops non-int processes) — instant false anomalies
        return gen_mod.clients(gen_mod.limit(schedule.n_ops,
                                             gen_mod.Fn(f)))

    # cur starts None — the checker's CASRegister model begins
    # undefined, so a pre-first-write read must return None (a 0 here
    # would be an unlinearizable phantom and every trial would "fail").
    # "torn" latches a torn write's corrupt replica value until the
    # first determinate read observes it (reads inside a partition
    # crash, so the exposure may come long after the tear).
    state = {"cur": None, "i": 0, "member_flip": 0, "crashes": 0,
             "torn": None}

    def complete(ctx, op):
        i = state["i"]
        state["i"] = i + 1
        active = active_at(i)
        if cluster is not None:
            vclock["t"] = i * 0.01
            cluster.set_clock_rate(
                float(schedule.knobs.get("clock_rate", 2.0))
                if "clock-rate" in active else 1.0)
            for (s, _e, kind) in wins:
                if kind == "membership" and s == i:
                    mop = cluster.op({})
                    if isinstance(mop, dict):
                        val = cluster.invoke({}, mop)
                        state["_pending_member"] = (mop, val)
            pend = state.pop("_pending_member", None)
            if pend is not None and cluster.resolve_op({}, pend) is None:
                state["_pending_member"] = pend
        f, value = op["f"], op["value"]
        latency_ms = 5.0 + fault_rng.random() * 10.0
        if "clock-rate" in active:
            latency_ms *= 1.0 / float(
                schedule.knobs.get("clock_rate", 2.0))
        if "pause" in active:
            latency_ms *= 5.0
        torn = bug.on_op(f, active) if bug is not None else False
        comp = dict(op)
        comp["time"] = op["time"] + ms_to_nanos(latency_ms)
        if "net" in active and not torn:
            crash = (state["crashes"] < MAX_CRASHES
                     and fault_rng.random() < 0.5)
            if crash:
                # indeterminate: the partitioned client never hears
                # back; a seeded coin decides whether the effect landed
                state["crashes"] += 1
                applied = fault_rng.random() < 0.5
                if applied and f == "write":
                    state["cur"] = value
                elif applied and f == "cas" \
                        and state["cur"] == value[0]:
                    state["cur"] = value[1]
                comp["type"] = "info"
            else:
                # connection refused: determinate failure, no effect
                comp["type"] = "fail"
            return comp
        if f == "read":
            comp["type"] = "ok"
            if state["torn"] is not None:
                # the read lands on the torn replica: a value nobody
                # ever wrote, which no linearization can explain
                comp["value"] = state["torn"]
                state["torn"] = None
            else:
                comp["value"] = state["cur"]
            return comp
        if f == "write":
            state["cur"] = value
            if torn:
                # torn write: acked ok, applied — but one replica is
                # left holding out-of-domain corrupt bytes
                state["torn"] = N_VALUES + i
            comp["type"] = "ok"
            return comp
        # cas
        if state["cur"] == value[0] and not torn:
            state["cur"] = value[1]
            comp["type"] = "ok"
        else:
            comp["type"] = "ok" if torn else "fail"
        return comp

    history = simulate({"concurrency": schedule.concurrency}, mk_gen(),
                       complete, seed=schedule.seed,
                       limit=schedule.n_ops * 8,
                       max_wall_s=float(schedule.n_ops) * 8,
                       clock=StepClock(step_s=1.0), _lane=None)
    return _inject_nemesis(history, wins)


def _inject_nemesis(history: list[dict], wins) -> list[dict]:
    """Weaves begin/end nemesis ``:info`` ops into the history at the
    window boundaries (op-index space → just before the matching
    client invoke), so the fault×op interleaving is first-class
    history the coverage extractor and the trace plane both read."""
    starts: dict[int, list[str]] = {}
    ends: dict[int, list[str]] = {}
    member_seq = {"n": 0}
    for (s, e, kind) in wins:
        starts.setdefault(s, []).append(kind)
        if WINDOW_OPS[kind][1] is not None:
            ends.setdefault(e, []).append(kind)

    def nem_ops(i: int, t) -> list[dict]:
        out = []
        for kind in ends.get(i, ()):
            out.append({"type": "info", "process": "nemesis",
                        "f": WINDOW_OPS[kind][1], "value": None,
                        "time": t})
        for kind in starts.get(i, ()):
            f = WINDOW_OPS[kind][0]
            if kind == "membership":
                f = "grow" if member_seq["n"] % 2 else "shrink"
                member_seq["n"] += 1
            out.append({"type": "info", "process": "nemesis",
                        "f": f, "value": None, "time": t})
        return out

    out: list[dict] = []
    inv = 0
    for op in history:
        if op.get("type") == "invoke":
            out.extend(nem_ops(inv, op.get("time", 0)))
            inv += 1
        out.append(op)
    tail_t = (history[-1].get("time", 0) if history else 0)
    for i in sorted(set(list(starts) + list(ends))):
        if i >= inv:
            out.extend(nem_ops(i, tail_t))
    return out


def write_run(history: list[dict], run_dir) -> Path:
    """Persists one trial as a discoverable run dir: the WAL first
    (the daemon's admission ticket), then the authoritative
    ``history.jsonl`` that lets it finalize on the next poll."""
    run_dir = Path(run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    j = Journal(run_dir / WAL_NAME, fsync_interval_s=-1)
    j.append_many(history)
    j.close()
    with open(run_dir / "history.jsonl", "w", encoding="utf-8") as f:
        for op in history:
            f.write(json.dumps(op) + "\n")
    return run_dir


# owner: worker — process-pool entry (each pool worker runs trials
# sequentially from its own argument tuple; no shared state)
def pool_run_trial(args) -> tuple[int, list[dict]]:
    """Top-level (picklable) pool entry: ``(idx, schedule_json,
    run_dir, bug_spec)`` → ``(idx, history)``, with the run dir
    written as a side effect."""
    idx, schedule_json, run_dir, bug_spec = args
    schedule = Schedule.from_json(schedule_json)
    history = run_trial(schedule, bug=PlantedBug.from_spec(bug_spec))
    write_run(history, run_dir)
    return idx, history
