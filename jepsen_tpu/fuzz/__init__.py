"""Coverage-guided nemesis schedule fuzzing (doc/robustness.md
"Schedule fuzzing").

The checker fleet turned active bug hunter (ROADMAP item 5):
thousands of short deterministic fake-mode runs, each one a seeded
:mod:`jepsen_tpu.generator.simulate` trial under a mutated nemesis
schedule, verdicted in batch through the live daemon's ingest path.
Mutation is steered by a coverage map instead of blind randomness —
novel fault×op interleaving signatures, new checker-state regimes
(frontier cardinality buckets, ladder rung outcomes via
``coverage_probe()``), and shrinking frontier margins as a near-miss
signal. Failing schedules auto-minimize through the PR-8 ddmin and
land as replayable ``hunt/<id>/`` artifacts.

Modules:

* :mod:`~jepsen_tpu.fuzz.schedule` — the seed tuple: a JSON-stable
  nemesis schedule (generator seed, op budget, fault windows, knobs).
* :mod:`~jepsen_tpu.fuzz.corpus` — AFL-style corpus + seeded mutators.
* :mod:`~jepsen_tpu.fuzz.coverage` — the edge map and signal
  extraction.
* :mod:`~jepsen_tpu.fuzz.trial` — one schedule → one WAL-backed run.
* :mod:`~jepsen_tpu.fuzz.hunt` — the hunter loop, artifacts, replay.
"""
from jepsen_tpu.fuzz.schedule import Schedule  # noqa: F401
