"""The hunter: coverage-guided schedule search over the live fleet.

One hunt = ``fuzz_trials`` short fake-mode runs in batches: a pool
writes each batch's WAL-backed run dirs, one :class:`~jepsen_tpu.live.
daemon.LiveDaemon` per batch ingests and verdicts them through the
same path a production fleet uses (device checkers batch across
trials), and an ``on_final`` hook harvests each session's
``coverage_probe()`` before its tracker is popped. New edges and
shrinking near-miss margins promote schedules into the corpus; an
invalid verdict is an anomaly — minimized through the PR-8 ddmin
(:func:`jepsen_tpu.checker.explain.ddmin` over the schedule's fault
windows, then an op-budget truncation pass) and landed as a
``hunt/<id>/`` artifact whose stored seed tuple replays the failure
bit-identically (doc/robustness.md "Schedule fuzzing").

Knobs (test map / CLI / ``JEPSEN_TPU_FUZZ_*`` env twins; tolerant
coercion here, strictness in preflight's KNB rows): ``fuzz_trials``,
``fuzz_pool_workers``, ``fuzz_trial_ops``, ``fuzz_seed``.
"""
from __future__ import annotations

import json
import logging
import os
import random
import shutil
import threading
import time
from pathlib import Path

from jepsen_tpu import telemetry
from jepsen_tpu.fuzz.corpus import Corpus, mutate, random_schedule
from jepsen_tpu.fuzz.coverage import CoverageMap, history_edges
from jepsen_tpu.fuzz.schedule import Schedule
from jepsen_tpu.fuzz.trial import (
    PlantedBug, pool_run_trial, run_trial, write_run,
)

logger = logging.getLogger("jepsen.fuzz")

DEFAULT_TRIALS = 400
DEFAULT_POOL_WORKERS = 0     # 0 = inline (deterministic single-thread)
DEFAULT_TRIAL_OPS = 120
DEFAULT_SEED = 0
DEFAULT_BATCH = 24
HUNT_DIR = "hunt"

# the canned interleaving-gated bug (--demo-bug, the e2e): arms on a
# cas inside a partition, then a write inside clock skew, and finally
# tears a write acked while ALL FOUR fault kinds overlap — a
# composition one random draw can never contain (the blind generator
# emits at most 3 windows, so at most 3 distinct kinds), while
# coverage guidance builds it incrementally: each partial mask is a
# retained new-edge parent, add-window mutation stacks a fourth kind
# on a 3-kind parent, and splice unions two parents' windows
DEMO_BUG_SPEC = [
    [["net"], "cas"],
    [["clock-rate"], "write"],
    [["clock-rate", "membership", "net", "pause"], "write"],
]

# fuzz knob spec shared with preflight's KNB validation
# (analysis/preflight._NUMERIC_KNOBS): (key, default, min)
FUZZ_KNOBS = (
    ("fuzz_trials", DEFAULT_TRIALS, 1.0),
    ("fuzz_pool_workers", DEFAULT_POOL_WORKERS, 0.0),
    ("fuzz_trial_ops", DEFAULT_TRIAL_OPS, 8.0),
    ("fuzz_seed", DEFAULT_SEED, None),
)


def fuzz_knob(name: str, value, default: float, lo: float | None):
    """Tolerant numeric coercion with a ``JEPSEN_TPU_<NAME>`` env twin:
    explicit value wins, then the env var, then the default; garbage
    warns and falls back (preflight's KNB001/KNB002 rows are where
    strictness lives)."""
    if value is None:
        value = os.environ.get("JEPSEN_TPU_" + name.upper())
    if value is None or value == "":
        return default
    try:
        if isinstance(value, bool):
            raise ValueError("bool is not a number")
        v = float(value)
    except (TypeError, ValueError):
        logger.warning("fuzz knob %s=%r is not numeric; using default "
                       "%r", name, value, default)
        return default
    if lo is not None and v < lo:
        logger.warning("fuzz knob %s=%r below minimum %r; clamping",
                       name, value, lo)
        return lo
    return v


class Hunter:
    """One coverage-guided (or, for the baseline, blind-random) hunt.

    ``bug_spec`` plants a :class:`~jepsen_tpu.fuzz.trial.PlantedBug`
    into every trial's target — the seam the e2e/demo uses; production
    hunts run the honest register, where an invalid verdict would mean
    a real checker/simulator bug. The spec is stored in the artifact,
    so replay reconstructs the identical target."""

    def __init__(self, store_root, trials=None, pool_workers=None,
                 trial_ops=None, seed=None, guided: bool = True,
                 bug_spec=None, accelerator: str = "cpu",
                 registry=None, batch_size: int = DEFAULT_BATCH,
                 stop_on_first: bool = True):
        self.store_root = Path(store_root)
        self.trials = int(fuzz_knob("fuzz_trials", trials,
                                    DEFAULT_TRIALS, 1.0))
        self.pool_workers = int(fuzz_knob("fuzz_pool_workers",
                                          pool_workers,
                                          DEFAULT_POOL_WORKERS, 0.0))
        self.trial_ops = int(fuzz_knob("fuzz_trial_ops", trial_ops,
                                       DEFAULT_TRIAL_OPS, 8.0))
        self.seed = int(fuzz_knob("fuzz_seed", seed, DEFAULT_SEED,
                                  None))
        self.guided = guided
        self.bug_spec = bug_spec
        self.accelerator = accelerator
        self.registry = registry if registry is not None \
            else telemetry.Registry()
        self.batch_size = max(1, int(batch_size))
        self.stop_on_first = stop_on_first
        self.rng = random.Random(self.seed)
        self.covmap = CoverageMap()
        base = Schedule(seed=self.seed, n_ops=self.trial_ops)
        self.corpus = Corpus(base=base)
        self.anomalies: list[dict] = []
        self.trials_run = 0
        self.outcomes = {"valid": 0, "invalid": 0, "error": 0}

    # -- schedule generation --------------------------------------------

    def _next_schedule(self) -> Schedule:
        if not self.guided:
            # the blind baseline IS the fuzzer's own seed generator —
            # what the search would be without a corpus. Composition
            # beyond any single draw (schedules mutation/splice builds
            # out of retained parents) is exactly what guidance buys.
            return random_schedule(self.rng, n_ops=self.trial_ops)
        parent = self.corpus.pick(self.rng)
        splice = (self.corpus.pick(self.rng)
                  if len(self.corpus) > 1 and self.rng.random() < 0.3
                  else None)
        return mutate(parent, self.rng, splice_from=splice)

    # -- trial execution ------------------------------------------------

    def _run_batch_trials(self, schedules, batch_root: Path) -> dict:
        """Writes every trial's run dir; returns {idx: history}.
        Results are applied in trial-index order regardless of pool
        completion order — the corpus/coverage updates must not depend
        on worker scheduling."""
        jobs = [(i, s.to_json(),
                 str(batch_root / f"t{i:05d}" / "0"), self.bug_spec)
                for i, s in enumerate(schedules)]
        histories: dict[int, list] = {}
        if self.pool_workers <= 1:
            for job in jobs:
                idx, h = pool_run_trial(job)
                histories[idx] = h
            return histories
        try:
            import concurrent.futures as _fut
            with _fut.ProcessPoolExecutor(
                    max_workers=self.pool_workers) as pool:
                for idx, h in pool.map(pool_run_trial, jobs):
                    histories[idx] = h
            return histories
        except Exception:  # noqa: BLE001 — pool loss degrades, never kills
            logger.exception("process pool failed; falling back to a "
                             "thread pool")
        lock = threading.Lock()
        queue = list(jobs)

        # owner: worker — fuzzer pool thread: pops one trial job at a
        # time under the lock; writes only its own run dir + its slot
        # in the (lock-guarded) histories dict
        def worker():
            while True:
                with lock:
                    if not queue:
                        return
                    job = queue.pop(0)
                idx, h = pool_run_trial(job)
                with lock:
                    histories[idx] = h

        threads = [threading.Thread(target=worker, daemon=True,
                                    name=f"jepsen-fuzz-pool-{i}")
                   for i in range(self.pool_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return histories

    def _verdict_batch(self, batch_root: Path, n: int) -> dict:
        """Fleet-path verdicts: one LiveDaemon over the batch's trial
        run dirs, probes harvested via on_final before trackers pop.
        The dirs are passed explicitly — a fuzz trial lands complete
        (WAL + history.jsonl at once), which the store-root scan would
        reject as post-hoc territory."""
        from jepsen_tpu.live.daemon import LiveDaemon
        collected: dict[str, dict] = {}

        def on_final(tr, results):
            probe_fn = getattr(tr.session, "coverage_probe", None)
            collected[tr.name] = {
                "results": results,
                "verdict": dict(tr.last_verdict),
                "probe": probe_fn() if probe_fn is not None else {},
            }

        run_dirs = [batch_root / f"t{i:05d}" / "0" for i in range(n)]
        daemon = LiveDaemon(run_dirs=run_dirs, poll_s=0.01,
                            max_runs=max(32, self.batch_size),
                            check_budget_s=30.0,
                            accelerator=self.accelerator,
                            registry=self.registry, on_final=on_final)
        daemon.run_until_idle(timeout_s=max(60.0, 2.0 * n))
        return collected

    # -- the hunt loop --------------------------------------------------

    def run(self) -> dict:
        """Hunts until the trial budget is spent (or, with
        ``stop_on_first``, until an anomaly lands). Returns the summary
        the CLI prints and tests assert on."""
        t0 = time.perf_counter()
        reg = self.registry
        trials_c = reg.counter(
            "fuzz_trials_total",
            "schedule-fuzz trials by verdict outcome",
            labels=("outcome",))
        batch_no = 0
        work_root = self.store_root / "work"
        while self.trials_run < self.trials:
            n = min(self.batch_size, self.trials - self.trials_run)
            schedules = [self._next_schedule() for _ in range(n)]
            batch_root = work_root / f"b{batch_no:04d}"
            histories = self._run_batch_trials(schedules, batch_root)
            collected = self._verdict_batch(batch_root, n)
            found = None
            for i in range(n):
                got = collected.get(f"t{i:05d}") or {}
                verdict = got.get("verdict") or {}
                probe = got.get("probe") or {}
                valid = verdict.get("valid_so_far")
                outcome = ("valid" if valid is True
                           else "invalid" if valid is False
                           else "error")
                self.outcomes[outcome] += 1
                trials_c.inc(outcome=outcome)
                self.trials_run += 1
                edges = history_edges(histories.get(i) or [])
                edges += list(probe.get("edges") or ())
                new_edges = self.covmap.observe(edges)
                near_miss = self.covmap.observe_margin(
                    probe.get("margin"))
                if outcome == "invalid":
                    self.anomalies.append({
                        "schedule": schedules[i],
                        "verdict": verdict,
                        "results": got.get("results"),
                    })
                    if self.guided:
                        self.corpus.add(schedules[i], reason="anomaly")
                    if found is None:
                        found = i
                elif self.guided and new_edges:
                    self.corpus.add(schedules[i], reason="new-edge")
                elif self.guided and near_miss:
                    self.corpus.add(schedules[i], reason="near-miss")
            reg.gauge("fuzz_coverage_edges",
                      "distinct coverage edges discovered by the hunt"
                      ).set(float(len(self.covmap)))
            reg.gauge("fuzz_corpus_size",
                      "schedules retained in the fuzz corpus"
                      ).set(float(len(self.corpus)))
            if self.covmap.best_margin is not None:
                reg.gauge("fuzz_near_miss_margin",
                          "smallest surviving frontier seen (1 = one "
                          "linearization from a verdict flip)"
                          ).set(float(self.covmap.best_margin))
            # trial dirs are scratch: anomalies carry their whole
            # reproduction in the schedule, so the batch dir goes
            shutil.rmtree(batch_root, ignore_errors=True)
            batch_no += 1
            if found is not None and self.stop_on_first:
                break
        summary = {
            "trials": self.trials_run,
            "outcomes": dict(self.outcomes),
            "coverage_edges": len(self.covmap),
            "corpus_size": len(self.corpus),
            "best_margin": self.covmap.best_margin,
            "anomalies": len(self.anomalies),
            "wall_s": round(time.perf_counter() - t0, 3),
            "guided": self.guided,
            "seed": self.seed,
        }
        if self.anomalies:
            summary["hunt_ids"] = [self.land(a)
                                   for a in self.anomalies[:4]]
        return summary

    # -- minimization + artifacts ---------------------------------------

    def _trial_invalid(self, schedule: Schedule,
                       explain: bool = False) -> dict | None:
        """Direct (daemon-less) re-verdict for minimization probes:
        the batch path already proved the checker agrees with the
        post-hoc result, so ddmin probes use the cheap exact check.
        Adds ``_failed_client_op`` (client-invoke count up to the dying
        op — the op-budget shrink's target, distinct from the raw
        history index because nemesis ops pad the history). ``explain``
        turns the forensics pass on for the one check whose result the
        artifact keeps; probes leave it off (a probe wants a verdict,
        not a witness shrink)."""
        from jepsen_tpu.checker.linearizable import LinearizableChecker
        h = run_trial(schedule, bug=PlantedBug.from_spec(self.bug_spec))
        res = LinearizableChecker(accelerator="cpu").check(
            None, h, {"explain": bool(explain)})
        if res.get("valid?") is not False:
            return None
        res = dict(res)
        fop = res.get("failed-op")
        if fop is not None:
            inv = 0
            for op in h:  # failed-op IS history[i] (same object)
                if op.get("type") == "invoke" \
                        and isinstance(op.get("process"), int):
                    inv += 1
                if op is fop:
                    res["_failed_client_op"] = inv
                    break
        return res

    def minimize(self, schedule: Schedule) -> tuple[Schedule, dict]:
        """PR-8 ddmin over the schedule's fault windows, then a
        greedy op-budget truncation — the minimized schedule still
        produces an invalid verdict (re-proven on every probe)."""
        from jepsen_tpu.checker.explain import ddmin
        kept, info = ddmin(
            list(schedule.faults),
            lambda ws: self._trial_invalid(
                Schedule(seed=schedule.seed, n_ops=schedule.n_ops,
                         concurrency=schedule.concurrency, faults=ws,
                         knobs=dict(schedule.knobs))) is not None,
            budget=48)
        s = schedule.copy()
        s.faults = kept
        res = self._trial_invalid(s)
        # op-budget shrink: cut past the anomaly, then halve toward it
        failed = (res or {}).get("_failed_client_op")
        if failed is not None:
            for n_ops in (failed + 8, failed + 2):
                if n_ops < s.n_ops:
                    cand = s.copy()
                    cand.n_ops = n_ops
                    if self._trial_invalid(cand) is not None:
                        s = cand
        info["n_ops"] = s.n_ops
        return s, info

    def land(self, anomaly: dict) -> str:
        """Minimizes one anomaly and writes the ``hunt/<id>/``
        artifact bundle: seed tuple, minimized schedule, minimized
        history, verdict, and the explain payload."""
        schedule = anomaly["schedule"]
        minimized, shrink_info = self.minimize(schedule)
        res = self._trial_invalid(minimized, explain=True)
        if res is None:  # pragma: no cover — minimize re-proves each step
            minimized, res = schedule, self._trial_invalid(schedule,
                                                           explain=True)
        history = run_trial(minimized,
                            bug=PlantedBug.from_spec(self.bug_spec))
        hunt_id = minimized.key()
        d = self.store_root / HUNT_DIR / hunt_id
        d.mkdir(parents=True, exist_ok=True)
        (d / "schedule.json").write_text(schedule.to_json() + "\n")
        (d / "minimized.json").write_text(minimized.to_json() + "\n")
        with open(d / "history.jsonl", "w", encoding="utf-8") as f:
            for op in history:
                f.write(json.dumps(op) + "\n")
        meta = {
            "id": hunt_id,
            "seed_tuple": minimized.canonical(),
            "bug_spec": self.bug_spec,
            "shrink": shrink_info,
            "live_verdict": anomaly.get("verdict"),
            "edges": history_edges(history),
        }
        (d / "verdict.json").write_text(
            json.dumps({k: v for k, v in (res or {}).items()
                        if _jsonable(v)}, default=repr, indent=2) + "\n")
        (d / "hunt.json").write_text(json.dumps(meta, indent=2) + "\n")
        logger.info("anomaly landed: hunt/%s (windows %d -> %d, "
                    "n_ops -> %d)", hunt_id, len(schedule.faults),
                    len(minimized.faults), minimized.n_ops)
        return hunt_id


def _jsonable(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def replay(store_root, hunt_id: str) -> dict:
    """``jepsen-tpu hunt --replay <id>``: re-runs the minimized
    schedule from the stored seed tuple and checks the reproduction is
    bit-identical — history bytes AND verdict must match what the hunt
    landed. Returns {reproduced, identical, verdict, ...}."""
    d = Path(store_root) / HUNT_DIR / hunt_id
    minimized = Schedule.from_json((d / "minimized.json").read_text())
    meta = json.loads((d / "hunt.json").read_text())
    bug = PlantedBug.from_spec(meta.get("bug_spec"))
    history = run_trial(minimized, bug=bug)
    stored = (d / "history.jsonl").read_text()
    got = "".join(json.dumps(op) + "\n" for op in history)
    from jepsen_tpu.checker.linearizable import LinearizableChecker
    res = LinearizableChecker(accelerator="cpu").check(
        None, history, {"explain": False})
    return {
        "id": hunt_id,
        "identical": got == stored,
        "reproduced": res.get("valid?") is False,
        "valid?": res.get("valid?"),
        "n_ops": minimized.n_ops,
        "windows": len(minimized.faults),
    }


def list_hunts(store_root) -> list[dict]:
    """The landed anomalies under ``<store>/hunt/`` (web + CLI)."""
    root = Path(store_root) / HUNT_DIR
    out = []
    if not root.is_dir():
        return out
    for d in sorted(root.iterdir()):
        meta_p = d / "hunt.json"
        if not d.is_dir() or not meta_p.exists():
            continue
        try:
            meta = json.loads(meta_p.read_text())
        except (OSError, ValueError):
            continue
        seed = meta.get("seed_tuple") or {}
        out.append({"id": d.name,
                    "n_ops": seed.get("n_ops"),
                    "windows": len(seed.get("faults") or ()),
                    "seed": seed.get("seed")})
    return out
