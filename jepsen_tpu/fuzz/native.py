"""Differential WAL-parser fuzz harness (``jepsen-tpu fuzz-native``).

The native ingest spine's third correctness leg, after the JTN lint
rules and the sanitizer lanes (doc/static-analysis.md "Native code"):
seeded, grammar-aware byte mutants of realistic WAL traffic are fed
through the C ``ingest_chunk`` scanner — whole-buffer AND split at
adversarial chunk boundaries — and every execution asserts byte-exact
``(ops, consumed, torn, truncated)`` agreement with the pure-Python
tolerant parser (``journal.parse_wal_chunk_py``). A periodic lane
round-trips the mutant through a real file and
``journal.read_jsonl_tolerant`` as a third independent oracle.

Determinism is the contract libFuzzer corpora have and ad-hoc fuzzers
lack: exec ``i`` under master seed ``s`` derives its own
``random.Random(f"{s}:{i}")``, so the mutant stream is byte-identical
across runs, machines, and interpreter sessions (regression-pinned in
tests/test_lint_native.py), and a divergence artifact names the exact
``(seed, exec)`` that reproduces it.

Run it under the ASan+UBSan build (the default when the toolchain
supports it — ``columnar_c.san_env()``): a mutant that walks the C
scanner out of bounds without corrupting the visible result is
invisible to the differential but fatal to the sanitizer, and vice
versa a silent wrong-answer bug is invisible to ASan but caught here.
"""
from __future__ import annotations

import json
import os
import random
import time
from pathlib import Path

# -- corpus seeds --------------------------------------------------------
# Checked-in, not generated: the fuzzer's grammar knowledge lives here.
# Each seed is one nasty WAL shape the ingest spine must survive; the
# mutators splice, tear, and bit-rot them from there.

_OPS = b"".join(
    b'{"type":"invoke","f":"write","value":%d,"process":%d,"time":%d}\n'
    b'{"type":"ok","f":"write","value":%d,"process":%d,"time":%d}\n'
    % (v, p, t, v, p, t + 1)
    for v, p, t in ((3, 0, 11), (7, 1, 13), (9, 2, 17)))

SEEDS: tuple[tuple[str, bytes], ...] = (
    ("happy", _OPS
     + b'{"type":"invoke","f":"cas","value":[3,1],"process":1,"time":20}\n'
     + b'{"type":"ok","f":"cas","value":[3,1],"process":1,"time":21}\n'
     + b'{"type":"invoke","f":"read","value":null,"process":2,"time":22}\n'
     + b'{"type":"ok","f":"read","value":1,"process":2,"time":23}\n'),
    ("torn-final", _OPS
     + b'{"type":"invoke","f":"read","value":null,"process":0,"time":3'),
    ("torn-interior",
     b'{"type":"ok","f":"write","value":1,"process":0,"time":1}\n'
     b'{"type":"ok","f":"wri\n'
     b'{"type":"ok","f":"write","value":2,"process":0,"time":2}\n'
     b'}}}}\n'
     b'{"type":"ok","f":"write","value":3,"process":0,"time":3}\n'),
    ("unicode",
     b'{"u":"\\ud83d\\ude00 caf\\u00e9 \\ud800 \\u0000"}\n'
     b'{"v":"raw caf\xc3\xa9 \xe2\x82\xac"}\n'
     b'{"w":"\\n\\t\\"\\\\ \\/ \\b\\f\\r"}\n'),
    ("numbers",
     b'{"big":1180591620717411303424,"neg":-0,"tiny":1.5e-3}\n'
     b'{"huge":123456789012345678901234567890123456789,"z":-0.0}\n'
     b'{"e":1e308,"f":-1e-308,"g":0.1,"inf":Infinity,"nan":NaN}\n'),
    ("empties",
     b'\n   \n\t\n'
     b'{"type":"ok","f":"read","value":null,"process":0,"time":1}\n'
     b'\n \n'),
    ("fleet-chunk",  # one line long enough to straddle receiver chunks
     b'{"type":"ok","f":"txn","value":[' + b",".join(
         b"%d" % i for i in range(160)) + b'],"process":5,"time":9}\n'),
    ("nested",
     b'{"a":' + b"[" * 24 + b"1" + b"]" * 24 + b',"b":{"c":{"d":[{}]}}}\n'),
)

# -- seeded mutation operators -------------------------------------------

_BAD_UTF8 = (b"\x80", b"\xc0\xaf", b"\xed\xa0\x80", b"\xf8\x88",
             b"\xff\xfe", b"\xc3")


def _lines(data: bytes) -> list[bytes]:
    return data.split(b"\n")


def _op_splice(rng: random.Random, data: bytes) -> bytes:
    other = rng.choice(SEEDS)[1]
    a, b = _lines(data), _lines(other)
    cut_a = rng.randrange(len(a) + 1)
    cut_b = rng.randrange(len(b) + 1)
    return b"\n".join(a[:cut_a] + b[cut_b:])


def _op_shuffle(rng: random.Random, data: bytes) -> bytes:
    ls = _lines(data)
    rng.shuffle(ls)
    return b"\n".join(ls)


def _op_dup_line(rng: random.Random, data: bytes) -> bytes:
    ls = _lines(data)
    i = rng.randrange(len(ls))
    return b"\n".join(ls[:i] + [ls[i]] * rng.randint(2, 4) + ls[i + 1:])


def _op_drop_line(rng: random.Random, data: bytes) -> bytes:
    ls = _lines(data)
    i = rng.randrange(len(ls))
    return b"\n".join(ls[:i] + ls[i + 1:])


def _op_truncate(rng: random.Random, data: bytes) -> bytes:
    if not data:
        return data
    return data[:rng.randrange(len(data))]


def _op_bit_flip(rng: random.Random, data: bytes) -> bytes:
    if not data:
        return data
    buf = bytearray(data)
    for _ in range(rng.randint(1, 8)):
        buf[rng.randrange(len(buf))] ^= 1 << rng.randrange(8)
    return bytes(buf)


def _op_byte_edit(rng: random.Random, data: bytes) -> bytes:
    buf = bytearray(data)
    for _ in range(rng.randint(1, 6)):
        which = rng.randrange(3)
        pos = rng.randrange(len(buf) + 1) if buf else 0
        if which == 0 or not buf:
            buf[pos:pos] = bytes([rng.randrange(256)])
        elif which == 1:
            del buf[pos % len(buf)]
        else:
            buf[pos % len(buf)] = rng.randrange(256)
    return bytes(buf)


def _op_huge_int(rng: random.Random, data: bytes) -> bytes:
    """Grows a digit run into a 60-300 digit integer — the 2^70 class
    the columnar value-encoder must route to the bignum path."""
    runs = [i for i, c in enumerate(data) if 0x31 <= c <= 0x39]
    if not runs:
        return data + b'{"v":%s}\n' % (b"9" * rng.randint(60, 300))
    i = rng.choice(runs)
    digits = bytes(rng.choice(b"0123456789") for _ in
                   range(rng.randint(60, 300)))
    return data[:i] + digits + data[i:]


def _op_bad_utf8(rng: random.Random, data: bytes) -> bytes:
    buf = bytearray(data)
    for _ in range(rng.randint(1, 3)):
        pos = rng.randrange(len(buf) + 1) if buf else 0
        buf[pos:pos] = rng.choice(_BAD_UTF8)
    return bytes(buf)


def _op_mid_splice(rng: random.Random, data: bytes) -> bytes:
    """Joins two seeds cut at arbitrary BYTE offsets — the shape a
    fleet receiver sees when a sender dies mid-frame."""
    other = rng.choice(SEEDS)[1]
    a = data[:rng.randrange(len(data) + 1)]
    b = other[rng.randrange(len(other) + 1):]
    return a + b


OPERATORS: tuple[tuple[str, object], ...] = (
    ("splice", _op_splice),
    ("shuffle", _op_shuffle),
    ("dup-line", _op_dup_line),
    ("drop-line", _op_drop_line),
    ("truncate", _op_truncate),
    ("bit-flip", _op_bit_flip),
    ("byte-edit", _op_byte_edit),
    ("huge-int", _op_huge_int),
    ("bad-utf8", _op_bad_utf8),
    ("mid-splice", _op_mid_splice),
)

_MAX_MUTANT = 1 << 16  # mutants never grow unboundedly across stacking


def mutant(rng: random.Random) -> tuple[bytes, str, list[str]]:
    """One mutant: a corpus seed pushed through 1-3 stacked operators.
    Returns ``(data, seed_name, operator_names)``."""
    seed_name, data = rng.choice(SEEDS)
    names: list[str] = []
    for _ in range(rng.randint(1, 3)):
        name, op = rng.choice(OPERATORS)
        data = op(rng, data)[:_MAX_MUTANT]
        names.append(name)
    return data, seed_name, names


def exec_rng(master_seed: int, i: int) -> random.Random:
    """The per-exec RNG: derived from ``(master_seed, exec index)`` via
    string seeding (SHA-512 under the hood — stable across processes
    and machines, unlike ``hash()``)."""
    return random.Random(f"jtfuzz:{master_seed}:{i}")


def mutant_stream(master_seed: int, n: int):
    """Yields ``(i, data, seed_name, operator_names)`` for execs
    ``0..n-1`` — the exact inputs ``run_fuzz`` executes, exposed so the
    determinism test can pin byte-identity without running the parsers."""
    for i in range(n):
        data, seed_name, names = mutant(exec_rng(master_seed, i))
        yield i, data, seed_name, names


# -- execution ------------------------------------------------------------

def _chunked(parse, data: bytes, cuts: list[int], final: bool):
    """Feeds ``data`` split at ``cuts`` through ``parse`` with the
    tailer's carry protocol (unconsumed remainder prepends the next
    piece). The chunk contract says the aggregate must equal the
    whole-buffer call."""
    bounds = [0] + cuts + [len(data)]
    ops: list = []
    torn = 0
    total = 0
    truncated = False
    buf = b""
    for k in range(len(bounds) - 1):
        buf += data[bounds[k]:bounds[k + 1]]
        last = k == len(bounds) - 2
        o, c, t, tr = parse(buf, last and final)
        ops.extend(o)
        torn += t
        total += c
        truncated = bool(tr)
        buf = buf[c:]
    return ops, total, torn, truncated


def _agree(py, nat) -> bool:
    from jepsen_tpu.history_ir import ingest
    return (ingest._deep_eq(list(py[0]), list(nat[0]))
            and py[1] == nat[1] and py[2] == nat[2]
            and bool(py[3]) == bool(nat[3]))


def _write_divergence(store: Path, i: int, master_seed: int, data: bytes,
                      mode: str, py, nat) -> Path:
    d = store / f"div-{i:08d}"
    d.mkdir(parents=True, exist_ok=True)
    (d / "input.bin").write_bytes(data)
    meta = {
        "seed": master_seed, "exec": i, "mode": mode,
        "python": {"ops": repr(py[0])[:4000], "consumed": py[1],
                   "torn": py[2], "truncated": bool(py[3])},
        "native": {"ops": repr(nat[0])[:4000], "consumed": nat[1],
                   "torn": nat[2], "truncated": bool(nat[3])},
        "repro": f"jepsen-tpu fuzz-native --seed {master_seed} "
                 f"--execs {i + 1}",
    }
    (d / "meta.json").write_text(json.dumps(meta, indent=2))
    return d

_MAX_DIVERGENCES = 25   # stop writing artifacts past this; abort run
_FILE_CHECK_EVERY = 509  # prime stride for the read_jsonl_tolerant lane


def run_fuzz(execs: int, seed: int = 0, san: bool = False,
             store_dir: str = "store", log_every: int = 10_000,
             progress=None) -> dict:
    """The harness loop. Returns a stats dict; ``status`` is ``"ok"``,
    ``"divergence"``, or ``"no-native"`` (toolchain/variant missing —
    the CLI decides whether that's an error)."""
    from jepsen_tpu.history_ir import ingest
    from jepsen_tpu.journal import parse_wal_chunk_py, read_jsonl_tolerant
    from jepsen_tpu.native import columnar_c

    m = columnar_c.mod(san=san)
    if m is None or not hasattr(m, "ingest_chunk"):
        if san:
            ingest.fallback_count("san-unavailable")
        return {"status": "no-native", "san": san, "execs": 0,
                "divergences": 0}

    def native_parse(chunk: bytes, final: bool):
        return m.ingest_chunk(chunk, final, ingest._line_fallback,
                              ingest._SKIP, ingest._TORN)

    from jepsen_tpu import telemetry
    store = Path(store_dir) / "fuzz-native"
    reg = telemetry.get_registry()
    exec_ctr = reg.counter("fuzz_native_execs_total",
                           "differential fuzz executions")
    div_ctr = reg.counter("fuzz_native_divergence_total",
                          "C-vs-Python parser divergences found by fuzzing")
    seed_hits: dict[str, int] = {}
    op_hits: dict[str, int] = {}
    divergences: list[str] = []
    ops_total = 0
    torn_total = 0
    flushed = 0
    i = -1
    t0 = time.monotonic()

    for i in range(execs):
        rng = exec_rng(seed, i)
        data, seed_name, op_names = mutant(rng)
        seed_hits[seed_name] = seed_hits.get(seed_name, 0) + 1
        for n in op_names:
            op_hits[n] = op_hits.get(n, 0) + 1
        final = rng.random() < 0.5

        py = parse_wal_chunk_py(data, final=final)
        nat = native_parse(data, final)
        bad = None
        if not _agree(py, nat):
            bad = ("whole", py, nat)
        else:
            ncuts = rng.randint(1, 4)
            cuts = sorted(rng.randrange(len(data) + 1)
                          for _ in range(ncuts))
            pyc = _chunked(parse_wal_chunk_py, data, cuts, final)
            natc = _chunked(native_parse, data, cuts, final)
            if not _agree(pyc, natc):
                bad = (f"chunked@{cuts}", pyc, natc)
            elif not _agree(py, pyc):
                # the Python twin disagreeing with ITSELF across chunk
                # boundaries is a protocol bug, not a C bug — still fatal
                bad = (f"protocol@{cuts}", py, pyc)
        if bad is None and i % _FILE_CHECK_EVERY == 0 and b"\r" not in data:
            # third oracle: the file-based tolerant reader. \r excluded
            # (text-mode universal newlines split on it; the byte
            # protocol intentionally does not). The appended newline
            # makes the tail complete so both sides agree final-line
            # semantics.
            fdata = data if data.endswith(b"\n") else data + b"\n"
            fpath = store / f"tmp-{os.getpid()}.jsonl"
            fpath.parent.mkdir(parents=True, exist_ok=True)
            fpath.write_bytes(fdata)
            try:
                rows, ftrunc = read_jsonl_tolerant(fpath)
            finally:
                fpath.unlink(missing_ok=True)
            fops = parse_wal_chunk_py(fdata, final=True)[0]
            if not ingest._deep_eq(rows, list(fops)) or ftrunc:
                bad = ("file-oracle", (fops, len(fdata), 0, False),
                       (rows, len(fdata), 0, ftrunc))
        if bad is not None:
            mode, want, got = bad
            div_ctr.inc()
            if len(divergences) < _MAX_DIVERGENCES:
                d = _write_divergence(store, i, seed, data, mode, want,
                                      got)
                divergences.append(str(d))
            if progress:
                progress(f"DIVERGENCE exec={i} mode={mode} -> "
                         f"{divergences[-1] if divergences else '(capped)'}")
            if len(divergences) >= _MAX_DIVERGENCES:
                break
        ops_total += len(py[0])
        torn_total += py[2]
        if (i + 1) % log_every == 0:
            exec_ctr.inc(log_every)
            flushed += log_every
            if progress:
                el = time.monotonic() - t0
                progress(f"  {i + 1}/{execs} execs, "
                         f"{(i + 1) / el:,.0f}/s, "
                         f"{len(divergences)} divergence(s)")
    done = i + 1
    if done > flushed:
        exec_ctr.inc(done - flushed)
    elapsed = time.monotonic() - t0
    return {
        "status": "divergence" if divergences else "ok",
        "san": san,
        "execs": done,
        "elapsed_s": elapsed,
        "execs_per_s": done / elapsed if elapsed > 0 else 0.0,
        "divergences": len(divergences),
        "artifacts": divergences,
        "ops_parsed": ops_total,
        "torn_lines": torn_total,
        "seed_coverage": seed_hits,
        "operator_coverage": op_hits,
    }
