"""The fuzzer's unit of search: a deterministic nemesis schedule.

A :class:`Schedule` is the complete seed tuple for one fake-mode trial
— generator seed, client-op budget, concurrency, fault windows, and
fake-cluster knobs. Trials are pure functions of it (the simulator's
wall cap rides a virtual clock, the fault model draws from the
schedule's own rng), so a stored schedule IS the reproduction:
``jepsen-tpu hunt --replay <id>`` re-runs it bit-identically.

Windows live in *op-index fraction* space (``start``/``dur`` in
[0, 1) of the trial's op budget), not wall time — mutation then
composes with op-budget mutation without re-anchoring, and the same
schedule scales to a longer trial for minimization experiments.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json

# window kinds the fake-mode fault model implements, with their
# begin/end nemesis op names (classified by nemesis/faults.classify —
# the trace/fault-window machinery must see these as real windows).
# membership is a one-shot reconfiguration: a begin op, no end op
# (healed by resolution), exactly the real MembershipNemesis contract.
WINDOW_OPS = {
    "net": ("start-partition", "stop-partition"),
    "clock-rate": ("start-clock-rate", "stop-clock-rate"),
    "pause": ("pause", "resume"),
    "membership": ("grow", None),
}
FAULT_KINDS = tuple(WINDOW_OPS)


@dataclasses.dataclass
class Schedule:
    """One point in schedule space. ``faults`` is a list of
    ``{"kind", "start", "dur"}`` dicts; ``knobs`` feeds
    ``FakeClusterState`` (settle window, member floor)."""

    seed: int = 0
    n_ops: int = 120
    concurrency: int = 3
    faults: list = dataclasses.field(default_factory=list)
    knobs: dict = dataclasses.field(default_factory=dict)

    def canonical(self) -> dict:
        return {
            "seed": int(self.seed),
            "n_ops": int(self.n_ops),
            "concurrency": int(self.concurrency),
            "faults": [{"kind": str(w["kind"]),
                        "start": round(float(w["start"]), 6),
                        "dur": round(float(w["dur"]), 6)}
                       for w in self.faults],
            "knobs": {str(k): self.knobs[k] for k in sorted(self.knobs)},
        }

    def to_json(self) -> str:
        return json.dumps(self.canonical(), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Schedule":
        d = json.loads(text)
        return cls(seed=int(d.get("seed", 0)),
                   n_ops=int(d.get("n_ops", 120)),
                   concurrency=int(d.get("concurrency", 3)),
                   faults=list(d.get("faults") or []),
                   knobs=dict(d.get("knobs") or {}))

    def key(self) -> str:
        """Stable content id — the hunt artifact directory name and the
        corpus dedup key."""
        return hashlib.sha256(self.to_json().encode()).hexdigest()[:12]

    def copy(self) -> "Schedule":
        return Schedule.from_json(self.to_json())

    def windows_ops(self) -> list[tuple[int, int, str]]:
        """Windows resolved to op-index space: ``(start_idx, end_idx,
        kind)``, end exclusive, each window at least one op wide."""
        out = []
        for w in self.faults:
            start = max(0, min(self.n_ops - 1,
                               int(float(w["start"]) * self.n_ops)))
            width = max(1, int(float(w["dur"]) * self.n_ops))
            out.append((start, min(self.n_ops, start + width),
                        str(w["kind"])))
        return out
