"""Coverage signals: what makes one schedule worth keeping.

Three signal families feed one edge set (doc/robustness.md "Schedule
fuzzing"):

* **fault×op interleavings** — from the trial history itself: each
  client completion is keyed by the fault-kind set active at that
  instant (derived from the nemesis ``:info`` ops the trial wove in,
  classified exactly as the PR-15 trace/fault-window machinery does),
  its ``:f``, and its completion type. ``op:net+clock-rate:write:ok``
  first appearing means some schedule drove a determinate write
  through an overlapping partition+clock-skew — territory blind
  randomness rarely composes.
* **checker-state transitions** — ``coverage_probe()`` edges from the
  live session (frontier cardinality buckets, ladder rung regimes).
* **near-miss margins** — not edges: the frontier's smallest surviving
  configuration count. A shrinking margin means the schedule walked to
  the cliff's edge; the corpus promotes it even with zero new edges.
"""
from __future__ import annotations

from jepsen_tpu.nemesis.faults import classify

# membership windows have no end op (healed by resolution); for the
# interleaving signature treat a begin as active for this many client
# invocations — the convergence horizon, not a real heal
MEMBERSHIP_HORIZON_OPS = 12


def history_edges(history: list[dict]) -> list[str]:
    """Fault×op interleaving signatures of one trial history."""
    edges: set[str] = set()
    active: dict[str, int] = {}
    member_left = 0
    for op in history or ():
        f = op.get("f")
        if op.get("process") == "nemesis":
            if op.get("type") != "info":
                continue
            phase, kind = classify(f)
            if kind is None:
                continue
            if kind == "membership":
                member_left = MEMBERSHIP_HORIZON_OPS
            elif phase == "begin":
                active[kind] = active.get(kind, 0) + 1
            elif phase == "end" and active.get(kind):
                active[kind] -= 1
                if not active[kind]:
                    del active[kind]
            continue
        typ = op.get("type")
        if typ == "invoke":
            if member_left:
                member_left -= 1
            continue
        kinds = sorted(k for k, n in active.items() if n)
        if member_left:
            kinds = sorted(kinds + ["membership"])
        mask = "+".join(kinds) or "none"
        edges.add(f"op:{mask}:{f}:{typ}")
    return sorted(edges)


class CoverageMap:
    """The global edge set plus the best (smallest) near-miss margin.
    ``observe`` returns how many edges were NEW — the guidance signal
    the corpus promotes on."""

    def __init__(self):
        self.edges: set[str] = set()
        self.best_margin: int | None = None

    def observe(self, edges) -> int:
        new = 0
        for e in edges or ():
            if e not in self.edges:
                self.edges.add(e)
                new += 1
        return new

    def observe_margin(self, margin) -> bool:
        """True when ``margin`` beats (shrinks below) the best seen —
        the near-miss promotion trigger."""
        if margin is None:
            return False
        m = int(margin)
        if self.best_margin is None or m < self.best_margin:
            self.best_margin = m
            return True
        return False

    def __len__(self) -> int:
        return len(self.edges)
