"""AFL-style schedule corpus: retention + seeded mutation.

Everything draws from the caller's ``random.Random`` — the hunt's
``fuzz_seed`` fully determines which parents are picked and how they
mutate, so a whole hunt replays bit-identically (the determinism the
quick-lane tests pin).
"""
from __future__ import annotations

import random

from jepsen_tpu.fuzz.schedule import FAULT_KINDS, Schedule

MAX_WINDOWS = 6


def random_schedule(rng: random.Random, n_ops: int = 120,
                    max_windows: int = 3) -> Schedule:
    """A uniformly random point in schedule space — the blind-random
    baseline's generator AND the mutation space's reference: mutation
    can reach anything this can emit."""
    faults = [_random_window(rng)
              for _ in range(rng.randint(0, max_windows))]
    return Schedule(seed=rng.getrandbits(32), n_ops=n_ops,
                    concurrency=rng.randint(2, 4), faults=faults,
                    knobs=_random_knobs(rng))


def _random_window(rng: random.Random) -> dict:
    return {"kind": rng.choice(FAULT_KINDS),
            "start": round(rng.random() * 0.9, 4),
            "dur": round(0.05 + rng.random() * 0.4, 4)}


def _random_knobs(rng: random.Random) -> dict:
    return {"settle_s": rng.choice((0.0, 0.01, 0.05)),
            "min_members": rng.randint(1, 4),
            "clock_rate": rng.choice((0.5, 2.0, 5.0))}


def mutate(schedule: Schedule, rng: random.Random,
           splice_from: Schedule | None = None) -> Schedule:
    """One seeded mutation step. Operators (picked by the rng):
    timing jiggle, window add/remove/kind-swap, knob mutation, seed
    reroll, op-budget nudge — plus AFL-style splice when a second
    parent is offered (the union of two parents' windows is how the
    hunt composes partial interleavings into overlapping ones)."""
    s = schedule.copy()
    if splice_from is not None and splice_from.faults \
            and rng.random() < 0.5:
        take = rng.randint(1, len(splice_from.faults))
        pool = list(splice_from.faults)
        rng.shuffle(pool)
        s.faults = (s.faults + pool[:take])[:MAX_WINDOWS]
        return s
    op = rng.randrange(6)
    if op == 0 and s.faults:  # jiggle one window's timing
        w = rng.choice(s.faults)
        w["start"] = round(min(0.95, max(
            0.0, float(w["start"]) + rng.uniform(-0.15, 0.15))), 4)
        w["dur"] = round(min(0.6, max(
            0.02, float(w["dur"]) + rng.uniform(-0.1, 0.1))), 4)
    elif op == 1 and len(s.faults) < MAX_WINDOWS:  # add a window
        s.faults.append(_random_window(rng))
    elif op == 2 and s.faults:  # drop a window
        s.faults.pop(rng.randrange(len(s.faults)))
    elif op == 3 and s.faults:  # swap a window's kind
        rng.choice(s.faults)["kind"] = rng.choice(FAULT_KINDS)
    elif op == 4:  # knob mutation
        s.knobs.update(_random_knobs(rng))
    else:  # reroll the generator seed / nudge the op budget
        s.seed = rng.getrandbits(32)
        if rng.random() < 0.3:
            s.n_ops = max(40, min(400, s.n_ops + rng.choice(
                (-40, -20, 20, 40))))
    if not s.faults:
        s.faults.append(_random_window(rng))
    return s


class Corpus:
    """Retained schedules with pick weighting toward recent additions
    (new coverage lives at the frontier of the search, so the newest
    entries are the most promising parents — the classic AFL queue
    bias, deterministic here because the pick rng is the hunt's)."""

    def __init__(self, base: Schedule | None = None):
        self.entries: list[dict] = []
        self.seen: set[str] = set()
        if base is not None:
            self.add(base, reason="seed")

    def add(self, schedule: Schedule, reason: str = "new-edge") -> bool:
        key = schedule.key()
        if key in self.seen:
            return False
        self.seen.add(key)
        self.entries.append({"schedule": schedule, "key": key,
                             "reason": reason})
        return True

    def pick(self, rng: random.Random) -> Schedule:
        if not self.entries:
            return random_schedule(rng)
        n = len(self.entries)
        # triangular bias toward the tail (newest)
        i = max(rng.randint(0, n - 1), rng.randint(0, n - 1))
        return self.entries[i]["schedule"]

    def __len__(self) -> int:
        return len(self.entries)
