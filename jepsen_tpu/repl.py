"""Interactive conveniences (reference: jepsen/src/jepsen/repl.clj)."""
from __future__ import annotations

from jepsen_tpu import store


def latest_test(store_dir: str = store.BASE_DIR):
    """Loads the most recently-run test's results (repl.clj:6)."""
    latest = store.latest(store_dir)
    if latest is None:
        return None
    name, ts, _path = latest
    return {
        "test": store.load_test(name, ts, store_dir),
        "results": store.load_results(name, ts, store_dir),
    }
