"""Pipelined device dispatch: overlap host staging with device compute.

On a tunnel-attached accelerator every dispatch/readback pair costs a
~100 ms round trip, and the checker's batch paths (jitlin's
transfer-matrix sub-dispatches, the segmented scale chain) are sequences
of bounded dispatches whose HOST side — prepass, grid build, interning,
H2D staging — can run entirely under the previous dispatch's device
compute. JAX dispatch is already asynchronous; what this module adds is
the discipline and the evidence:

* :class:`DispatchPipeline` — a bounded-depth dispatch queue. Each
  ``submit(prep_fn, dispatch_fn)`` runs the host staging, issues the
  async dispatch, and tracks the unsynced device handles; when more than
  ``depth`` dispatches are outstanding the OLDEST is blocked on first
  (delayed blocking), so ≥ 2 sub-batches stay in flight while device
  memory stays bounded. ``results()`` performs ONE batched host
  transfer at the very end — never a readback per sub-batch.
* Occupancy accounting — how much host staging time was hidden under
  in-flight device work, stall time spent at the depth limit, and the
  in-flight high-water — wired into the telemetry registry
  (``dispatch_*`` instruments) and mirrored into the thread-local
  :func:`last_stats` so
  bench.py can fold the numbers into its summary line.
* A round-trip cost model (:class:`CostModel`) for ``accelerator=auto``
  routing: when the CPU lane can finish a batch before the device's
  round-trip floor, the batch routes to the C++/CPU lane instead of
  eating the tunnel latency (VERDICT r4 #4 / r5 weak #2 — sub-128-key
  ``independent`` batches were latency-bound, not compute-bound).

The pipeline is deliberately host-synchronous: ``submit`` runs prep on
the calling thread (numpy prep work is GIL-bound anyway) and relies on
the device runtime for the actual overlap. That keeps results
DETERMINISTIC — submission order is result order, and a pipelined run
is bit-identical to a serial one (tests/test_pipeline.py pins this
against the un-pipelined path).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger("jepsen.pipeline")

# Stats of the calling thread's most recently completed pipeline
# (results() updates it): bench.py reads this after a timed stage the
# way elle's bench reads its phase dict. Thread-local — concurrent
# checkers under bounded_pmap must not clobber each other's stats.
_LAST_STATS = threading.local()


def last_stats() -> dict:
    """The calling thread's most recent pipeline stats ({} if none)."""
    return dict(getattr(_LAST_STATS, "value", {}))

# Default CPU-lane throughput estimate (events/sec) for the cost model
# before any measured sample lands: the r5 bench's directly-measured
# sequential CPU anchor checked ~95k ops/s = ~190k events/s on this
# host; half that is a conservative floor so auto-routing never sends
# device-sized work to a slower-than-expected CPU.
DEFAULT_CPU_EVENTS_PER_SEC = 100_000.0

_RTT_CACHE: dict = {}
_CPU_RATE: dict = {}
# measured checker throughput per mesh width: {n_devices: events/s EWMA}
# (n_devices=1 is the single-device lane). Feeds CostModel.mesh_route so
# a small batch is not sent to the mesh on faith.
_DEVICE_RATE: dict = {}

# Below this many events, a batch with no measured rates skips the mesh:
# the fixed mesh costs (per-device staging, divisibility padding, the
# verdict collective) can't amortize on tiny dispatches. Env-tunable for
# on-chip sweeps.
MESH_MIN_EVENTS = int(os.environ.get("JEPSEN_TPU_MESH_MIN_EVENTS",
                                     str(1 << 16)))
# with no measured single-device rate, every Nth mesh-eligible batch
# runs single-device instead — the probe that lets mesh_route's
# measured comparison activate (and demote a losing mesh) in workloads
# that would otherwise only ever sample the mesh width
MESH_PROBE_EVERY = 16
_MESH_PROBE_COUNT = 0


def measured_roundtrip_s() -> float:
    """One tiny H2D+D2H round trip (median of 3 after a warm-up, cached
    per process) — the fixed latency floor every device dispatch chain
    pays at least twice (first dispatch + final readback). The
    ``JEPSEN_TPU_RTT_S`` env var overrides (tests, known deployments);
    an unreachable backend reads as 0.0 so routing degrades to
    device-always rather than guessing."""
    env = os.environ.get("JEPSEN_TPU_RTT_S")
    if env:
        try:
            return float(env)
        except ValueError:
            logger.warning("ignoring malformed JEPSEN_TPU_RTT_S=%r", env)
    if "rtt" not in _RTT_CACHE:
        try:
            import jax
            import numpy as np
            x = np.zeros(8, np.float32)
            jax.device_get(jax.device_put(x))  # warm backend/compile
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.device_get(jax.device_put(x))
                ts.append(time.perf_counter() - t0)
            _RTT_CACHE["rtt"] = sorted(ts)[1]
        except Exception:  # noqa: BLE001 — no backend: never route on it
            _RTT_CACHE["rtt"] = 0.0
    return _RTT_CACHE["rtt"]


def observe_cpu_rate(n_events: int, seconds: float) -> None:
    """Feeds a measured CPU-lane sample into the cost model (EWMA) so
    routing tracks the actual host instead of the built-in default."""
    if seconds <= 0 or n_events <= 0:
        return
    rate = n_events / seconds
    prev = _CPU_RATE.get("events_per_sec")
    _CPU_RATE["events_per_sec"] = (rate if prev is None
                                   else 0.7 * prev + 0.3 * rate)


def cpu_events_per_sec() -> float:
    return _CPU_RATE.get("events_per_sec", DEFAULT_CPU_EVENTS_PER_SEC)


def observe_device_rate(n_devices: int, n_events: int,
                        seconds: float) -> None:
    """Feeds one measured device-lane sample into the per-device-count
    rate model (EWMA per mesh width). The first sample per width
    includes JIT compile — the EWMA washes it out within a few
    dispatches, and an under-estimate only means routing a batch to one
    device, the old behavior. Samples below a quarter of
    MESH_MIN_EVENTS are dropped: a tiny dispatch measures fixed
    overhead (compile, staging, the round trip), not throughput, and
    would mislead the route comparison at the large sizes where routing
    matters."""
    if (seconds <= 0 or n_events < max(1, MESH_MIN_EVENTS // 4)
            or n_devices < 1):
        return
    rate = n_events / seconds
    prev = _DEVICE_RATE.get(n_devices)
    _DEVICE_RATE[n_devices] = (rate if prev is None
                               else 0.7 * prev + 0.3 * rate)


def device_events_per_sec(n_devices: int) -> float | None:
    """The measured EWMA rate at a mesh width, or None (no sample)."""
    return _DEVICE_RATE.get(n_devices)


class CostModel:
    """Round-trip-vs-CPU routing for ``accelerator=auto``.

    The device floor for a pipelined batch is ~2 round trips (the first
    dispatch's H2D and the single batched readback; intermediate
    dispatches overlap). When the CPU lane's predicted time beats that
    floor, the device can only lose — route to CPU. Compute time on
    device is NOT modeled (it would need a per-kernel throughput model);
    the floor alone is what kills small batches on tunneled chips, and
    an under-estimate only means taking the device path, the old
    behavior."""

    def __init__(self, roundtrip_s: float | None = None,
                 cpu_events_per_sec_: float | None = None):
        self._rtt = roundtrip_s
        self._cpu_rate = cpu_events_per_sec_

    def rtt(self) -> float:
        return self._rtt if self._rtt is not None else measured_roundtrip_s()

    def cpu_rate(self) -> float:
        return (self._cpu_rate if self._cpu_rate is not None
                else cpu_events_per_sec())

    def cpu_seconds(self, total_events: int) -> float:
        return total_events / max(self.cpu_rate(), 1e-9)

    def device_floor_seconds(self) -> float:
        return 2.0 * self.rtt()

    def route(self, total_events: int) -> str:
        """"cpu" when the CPU lane beats the device round-trip floor,
        else "device"."""
        return ("cpu" if self.cpu_seconds(total_events)
                < self.device_floor_seconds() else "device")

    def admission_budget_ops(self, seconds: float) -> float:
        """How many events the CPU lane can verify in ``seconds`` — the
        live daemon's per-poll admission budget (one hot run may spend
        at most its share of this before the rest defer; the measured
        EWMA keeps it honest as the host load shifts)."""
        return max(0.0, seconds) * self.cpu_rate()

    def mesh_route(self, total_events: int, n_devices: int) -> bool:
        """Should a batch of ``total_events`` take the ``n_devices``
        mesh path? With measured rates at both widths, compare predicted
        times (the mesh side also pays ~1 extra round trip for the
        verdict collective + per-device staging); without evidence, gate
        on MESH_MIN_EVENTS so small batches never pay mesh overhead on
        faith. A wrong "no" is the old single-device behavior; a wrong
        "yes" self-corrects once the rates land — and because a
        mesh-dominated workload would otherwise never produce a
        single-device sample, every MESH_PROBE_EVERY-th eligible batch
        with no measured single-device rate runs single-device as a
        probe, so the comparison can activate and demote a losing
        mesh."""
        global _MESH_PROBE_COUNT
        if n_devices < 2:
            return False
        r1 = device_events_per_sec(1)
        rn = device_events_per_sec(n_devices)
        if r1 and rn:
            return (total_events / rn + self.rtt()
                    < total_events / r1)
        if total_events < MESH_MIN_EVENTS:
            return False
        if r1 is None:
            _MESH_PROBE_COUNT += 1
            if _MESH_PROBE_COUNT % MESH_PROBE_EVERY == 0:
                return False
        return True


_DEFAULT_MODEL = CostModel()


def auto_route(total_events: int) -> str:
    """Module-level routing with the process-default cost model."""
    return _DEFAULT_MODEL.route(total_events)


def mesh_route(total_events: int, n_devices: int) -> bool:
    """Module-level mesh gate with the process-default cost model."""
    return _DEFAULT_MODEL.mesh_route(total_events, n_devices)


def donate_ok() -> bool:
    """Should dispatches donate their carry buffers? Donation lets XLA
    reuse the previous segment's [B, MV, MV] operator product in place
    (halving the carry's HBM footprint on chained resume dispatches),
    but the CPU backend can't honor it and warns per call — gate on the
    default backend."""
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001
        return False


def _pending(handle) -> bool:
    """Is the dispatch still executing? jax arrays expose a non-blocking
    ``is_ready()``; an already-finished dispatch must NOT count as
    overlap (a host-bound pipeline would otherwise report near-perfect
    occupancy it never achieved). Objects without readiness (test
    fakes) count as pending."""
    try:
        import jax
        arrs = [l for l in jax.tree_util.tree_leaves(handle)
                if isinstance(l, jax.Array)]
        if arrs:
            return not all(a.is_ready() for a in arrs)
    except ImportError:
        pass
    is_ready = getattr(handle, "is_ready", None)
    return True if is_ready is None else not is_ready()


def _is_jax_tree(handle) -> bool:
    """Does the handle tree contain jax arrays? Distinguishes real
    dispatches from test fakes WITHOUT a blanket except that would
    also swallow genuine device failures."""
    try:
        import jax
        return any(isinstance(leaf, jax.Array)
                   for leaf in jax.tree_util.tree_leaves(handle))
    except ImportError:
        return False


def _block(handle) -> None:
    """Blocks until a dispatch's handles are ready. Works on jax arrays
    (tree), or any object exposing block_until_ready (test fakes).
    Device/runtime failures propagate — they must not read as a
    successful (zero-stall) block."""
    if _is_jax_tree(handle):
        import jax
        jax.block_until_ready(handle)
        return
    bur = getattr(handle, "block_until_ready", None)
    if bur is not None:
        bur()


class DispatchPipeline:
    """Bounded-depth async dispatch queue with occupancy accounting.

    ::

        pipe = DispatchPipeline(depth=2, name="matrix")
        for sub in sub_batches:
            pipe.submit(lambda: build_grids(sub),   # host staging
                        dispatch_kernel)            # async device call
        outs = pipe.results()                       # ONE batched fetch

    ``prep_fn()`` returns the dispatch args (a tuple, or a single value);
    ``dispatch_fn(*args)`` must return device handles WITHOUT reading
    them back. With ``dispatch_fn=None``, ``prep_fn`` does both and
    returns the handles directly. Results come back in submission
    order."""

    def __init__(self, depth: int = 2, name: str = "dispatch"):
        from jepsen_tpu import telemetry

        self.depth = max(1, depth)
        self.name = name
        self._handles: list = []
        self._inflight: deque = deque()
        self._t0 = time.perf_counter()
        self._prep_s = 0.0
        self._overlap_prep_s = 0.0
        self._stall_s = 0.0
        self._inflight_peak = 0
        self._reg = telemetry.get_registry()

    def stage(self, *arrays):
        """Issues async H2D copies for ``arrays`` (double-buffered by the
        runtime) so the transfer overlaps in-flight compute instead of
        serializing inside the jitted call."""
        import jax
        return [jax.device_put(a) for a in arrays]

    def submit(self, prep_fn, dispatch_fn=None):
        """Stages one sub-batch and dispatches it. Returns the unsynced
        handle (also tracked for results())."""
        # overlap is judged BEFORE prep runs and only against dispatches
        # still executing (non-blocking readiness probe): crediting any
        # prep-after-first-submit would report near-perfect occupancy
        # even when the device finished long before staging did
        was_computing = any(_pending(h) for h in self._inflight)
        t0 = time.perf_counter()
        staged = prep_fn()
        dt = time.perf_counter() - t0
        self._prep_s += dt
        if was_computing:
            # host staging that ran while >= 1 dispatch computed on
            # device: the time the pipeline actually hid
            self._overlap_prep_s += dt
        if len(self._inflight) >= self.depth:
            oldest = self._inflight.popleft()
            t1 = time.perf_counter()
            _block(oldest)
            self._stall_s += time.perf_counter() - t1
        if dispatch_fn is None:
            handle = staged
        else:
            args = staged if isinstance(staged, tuple) else (staged,)
            handle = dispatch_fn(*args)
        self._handles.append(handle)
        self._inflight.append(handle)
        self._inflight_peak = max(self._inflight_peak, len(self._inflight))
        if self._reg.enabled:
            self._reg.counter(
                "dispatch_batches_total", "sub-batches dispatched",
                labels=("queue",)).inc(queue=self.name)
            self._reg.gauge(
                "dispatch_inflight", "dispatches currently in flight",
                labels=("queue",)).set(len(self._inflight), queue=self.name)
            self._reg.gauge(
                "dispatch_inflight_peak", "in-flight high-water",
                labels=("queue",)).set_max(self._inflight_peak,
                                           queue=self.name)
        return handle

    def results(self) -> list:
        """ONE batched host transfer of every submitted handle, in
        submission order; finalizes the occupancy stats."""
        t1 = time.perf_counter()
        if _is_jax_tree(self._handles):
            # real dispatches: one batched readback; device failures
            # (worker crash, runtime fault) PROPAGATE — swallowing them
            # here would hand unsynced handles to the caller, whose
            # per-element reads would then pay a round trip each and
            # lose the original error
            import jax
            out = jax.device_get(self._handles)
        else:
            out = list(self._handles)  # test fakes
        sync_s = time.perf_counter() - t1
        wall = time.perf_counter() - self._t0
        overlap_frac = (self._overlap_prep_s / self._prep_s
                        if self._prep_s > 0 else 0.0)
        stats = {
            "queue": self.name,
            "batches": len(self._handles),
            "inflight_peak": self._inflight_peak,
            "host_prep_s": round(self._prep_s, 4),
            "overlapped_prep_s": round(self._overlap_prep_s, 4),
            "overlap_frac": round(overlap_frac, 4),
            "stall_s": round(self._stall_s, 4),
            "sync_s": round(sync_s, 4),
            "wall_s": round(wall, 4),
        }
        _LAST_STATS.value = stats
        if self._reg.enabled:
            self._reg.gauge(
                "dispatch_overlap_frac",
                "fraction of host staging hidden under device compute, "
                "last pipeline", labels=("queue",)
                ).set(overlap_frac, queue=self.name)
            self._reg.gauge(
                "dispatch_inflight", "dispatches currently in flight",
                labels=("queue",)).set(0, queue=self.name)
            self._reg.histogram(
                "dispatch_stall_seconds",
                "time blocked at the depth limit", labels=("queue",)
                ).observe(self._stall_s, queue=self.name)
            self._reg.histogram(
                "dispatch_sync_seconds", "final batched readback wait",
                labels=("queue",)).observe(sync_s, queue=self.name)
        self._inflight.clear()
        return out

    def stats(self) -> dict:
        """The finalized stats (valid after results())."""
        s = last_stats()
        return s if s.get("queue") == self.name else {}
