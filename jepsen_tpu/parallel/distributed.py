"""Multi-process device-mesh support: the DCN half of the scaling story
(SURVEY.md §5.8 — the reference scales its checker workers across hosts
with JVM threads + NCCL-style backends; here a multi-host run is N
Python processes under ``jax.distributed``, one global mesh whose
devices span processes, and the SAME shard_map/psum kernels — XLA's
collectives ride ICI within a host and DCN across hosts, no code
change).

The single-chip tunnel can't demonstrate multi-host, so the proof rides
CPU: each process forces ``--xla_force_host_platform_device_count=K``
and joins a 2-process coordinator, giving a 2K-device global mesh
(tests/test_distributed.py drives two real OS processes end to end —
the claim "runs under jax.distributed" is executed, not asserted).

Data placement is the only multi-process-specific piece: a process may
only materialize its own devices' shards, so global arrays are built
with ``make_array_from_process_local_data`` from per-process local
shards instead of ``device_put`` of a replicated numpy array.
"""
from __future__ import annotations

import numpy as np


def initialize(coordinator: str, num_processes: int, process_id: int,
               local_devices: int | None = None) -> None:
    """Joins the distributed runtime. Call before any backend use; on
    CPU, set ``local_devices`` to force a virtual device count (the
    XLA_FLAGS knob) for mesh tests without real hardware."""
    import os

    if local_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{local_devices}").strip()
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def global_mesh(axis: str = "edges"):
    """One mesh over every device of every process."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (axis,))


def local_mesh(axis: str = "keys", max_devices: int | None = None):
    """A mesh over THIS process's devices only, or None with fewer than
    two (``max_devices`` caps the width — pass
    parallel.mesh_devices_limit() so the JEPSEN_TPU_MESH_DEVICES global
    disable applies to multi-process runs too). The intra-host half of
    the multi-host decomposition: keys split by process over DCN
    (batch_check_distributed), then each process's slice shards over its
    own devices with the same shard_map kernels — a process can only
    materialize its own devices' shards, so the process-spanning global
    mesh must never be handed to a local batch_check."""
    import jax
    from jax.sharding import Mesh

    devs = jax.local_devices()
    if max_devices is not None:
        devs = devs[:max_devices]
    if len(devs) < 2:
        return None
    return Mesh(np.asarray(devs), (axis,))


def _place_local(mesh, local: np.ndarray):
    """Global sharded array from this process's shard (equal-length
    shards per process; caller pads)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    return jax.make_array_from_process_local_data(sharding, local)


def trim_to_cycles_distributed(n_nodes: int, local_src, local_dst, mesh,
                               max_iters: int = 512) -> np.ndarray:
    """Multi-process twin of ops.scc.trim_to_cycles_sharded: every
    process contributes its LOCAL edge shard (the global edge list is
    their concatenation in process order), the kernel is the shared
    run_sharded_trim — per-device partial degrees, psum-reduced — and
    the replicated activity mask comes back to every process.

    Local shards are padded to a common per-device length with weight-0
    edges; processes must pass equally-sized shards (pad with any node
    id, the weight zeroes it out).
    """
    import jax
    from jepsen_tpu.ops.scc import run_sharded_trim

    local_src = np.asarray(local_src, np.int32)
    local_dst = np.asarray(local_dst, np.int32)
    n_local_dev = len([d for d in mesh.devices.flat
                       if d.process_index == jax.process_index()])
    E = len(local_src)
    pad = (-E) % max(1, n_local_dev)
    sj = _place_local(mesh, np.concatenate(
        [local_src, np.zeros(pad, np.int32)]))
    dj = _place_local(mesh, np.concatenate(
        [local_dst, np.zeros(pad, np.int32)]))
    wj = _place_local(mesh, np.concatenate(
        [np.ones(E, np.int32), np.zeros(pad, np.int32)]))
    out = run_sharded_trim(mesh, n_nodes, sj, dj, wj, max_iters)
    # the mask is replicated (out_specs=P()), so it is fully addressable
    return np.asarray(out)


def localize_keys_distributed(streams, invalid_indices, step_ids=None,
                              step_py=None, init_state: int = 0):
    """Multi-host anomaly localization over an independent key batch
    (the forensics half of :func:`batch_check_distributed`): each
    process localizes the invalid keys of ITS contiguous slice on its
    local devices — ``jitlin.matrix_localize``'s chunk-product bisection
    when the key is in the matrix regime, the exact CPU frontier
    otherwise (``checker.explain.first_failure``) — and the per-key
    first-anomaly positions allgather, so every process returns the full
    ``{key_index: (failed_event, failed_op_index)}`` map. Like the
    verdict gather, the DCN carries only a few ints per key; the
    localization work itself never crosses a process boundary."""
    import jax
    from jax.experimental import multihost_utils

    from jepsen_tpu.checker.explain import first_failure

    streams = list(streams)
    wanted = sorted(int(i) for i in invalid_indices)
    n = len(streams)
    pid, n_proc = jax.process_index(), jax.process_count()
    lo = pid * n // n_proc
    hi = (pid + 1) * n // n_proc
    per = -(-n // n_proc)
    block = np.full((per, 3), -1, np.int64)
    for row, i in enumerate(range(lo, hi)):
        if i not in wanted:
            continue
        try:
            found = first_failure(streams[i], step_ids=step_ids,
                                  step_py=step_py, init_state=init_state)
        except Exception:  # noqa: BLE001 — forensics never fail the batch
            found = None
        if found is not None:
            block[row] = (i, found[0], found[1])
    gathered = np.asarray(
        multihost_utils.process_allgather(block)).reshape(n_proc, per, 3)
    out: dict[int, tuple[int, int]] = {}
    for p in range(n_proc):
        for key, ev, op in gathered[p]:
            if key >= 0:
                out[int(key)] = (int(ev), int(op))
    return out


def batch_check_distributed(streams, capacity: int = 256, kernel=None):
    """Multi-host jepsen.independent: every process checks its contiguous
    slice of the key batch on its LOCAL devices (independent keys are
    embarrassingly parallel, so the DCN carries only verdicts), then the
    per-key results allgather so each process returns the full list —
    the same [(alive, died, overflow, peak)] contract as
    parallel.batch_check.

    This is deliberately not edge-sharded like the trim: per-key
    linearizability has zero cross-key coupling, so the right multi-host
    decomposition is keys-by-process with one tiny collective at the
    end, not a sharded kernel with per-step DCN collectives."""
    import jax
    from jax.experimental import multihost_utils

    from jepsen_tpu.parallel import batch_check

    streams = list(streams)
    n = len(streams)
    pid, n_proc = jax.process_index(), jax.process_count()
    lo = pid * n // n_proc
    hi = (pid + 1) * n // n_proc
    # within the process, the slice may still shard over the LOCAL
    # devices (cost-gated like the single-host path); mesh=False remains
    # the floor so auto-detection can never grab the process-spanning
    # global mesh
    mesh = False
    if hi > lo:
        from jepsen_tpu import parallel
        from jepsen_tpu.parallel import pipeline
        lm = (local_mesh(max_devices=parallel.mesh_devices_limit())
              if parallel.sharded_enabled() else None)
        if lm is not None and pipeline.mesh_route(
                sum(len(s.kind) for s in streams[lo:hi]),
                int(lm.devices.size)):
            mesh = lm
    local = batch_check(streams[lo:hi], capacity=capacity, kernel=kernel,
                        mesh=mesh) if hi > lo else []
    # fixed-size per-process row block (keys aren't perfectly divisible):
    # pad with sentinel rows, mark validity in column 0
    per = -(-n // n_proc)
    block = np.full((per, 5), -1, np.int64)
    for i, (alive, died, ovf, peak) in enumerate(local):
        block[i] = (1, int(bool(alive)), int(died), int(bool(ovf)),
                    int(peak))
    # single-process allgather returns the block unstacked; normalize to
    # the (n_proc, per, 5) layout the unpack below expects
    gathered = np.asarray(
        multihost_utils.process_allgather(block)).reshape(n_proc, per, 5)
    out = []
    for p in range(n_proc):
        for row in gathered[p]:
            if row[0] == 1:
                out.append((bool(row[1]), int(row[2]), bool(row[3]),
                            int(row[4])))
    assert len(out) == n, (len(out), n)
    return out
