"""Device-mesh parallelism for the checker data plane.

The reference's 'distributed communication backend' is SSH fan-out
(SURVEY.md §5.8); ours is XLA collectives over a `jax.sharding.Mesh`. The
checker workloads are batch-parallel over keys (independent registers) and
graph-parallel over txn partitions, so the sharding story is:

* ``keys`` axis: per-key event tensors sharded over all devices; the
  jitlin kernel runs under vmap with inputs/outputs NamedSharding'd on the
  leading axis, so each device checks its shard of keys with zero
  cross-device traffic until the final verdict gather (ICI all-gather of
  B bools).
* SCC label propagation shards edges over devices and psums the label
  updates (see ops/scc.py) — collectives ride ICI on a pod.

Multi-host: ``parallel.distributed`` initializes ``jax.distributed``,
builds a process-spanning global mesh, places per-process edge shards
with make_array_from_process_local_data for the sharded trim (psum
crossing the process boundary), and splits independent key batches by
process with a verdict allgather. Exercised for real by
tests/test_distributed.py: two OS processes × 4 virtual CPU devices
form one 8-device mesh and run both paths end to end.
"""
from __future__ import annotations

import logging
import threading
from typing import Sequence

import numpy as np

logger = logging.getLogger("jepsen.parallel")


def devices():
    import jax
    return jax.devices()


def get_mesh(n_devices: int | None = None, axis: str = "keys"):
    """A 1-D mesh over available devices (jax.sharding.Mesh)."""
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


# One Mesh object per (device count, axis): jitlin's compile caches key
# on the mesh's device ids + axis names, but Mesh construction itself is
# cheap-ish yet NOT free, and handing callers the same object makes
# caching behavior obvious in traces.
_MESH_CACHE: dict = {}


def coerce_devices(value, knob: str = "mesh_devices") -> int | None:
    """Tolerant device-count knob coercion: None/'' read as unset,
    numeric strings work, garbage warns and reads as unset (the
    interpreter's knob-layer discipline — a bad sweep variable must
    not fail a run preflight already admitted)."""
    if value is None or value == "":
        return None
    if isinstance(value, bool):
        logger.warning("ignoring bool %s=%r (want a device count)",
                       knob, value)
        return None
    try:
        n = int(float(value))
    except (TypeError, ValueError):
        logger.warning("ignoring malformed %s=%r (want an int)",
                       knob, value)
        return None
    return max(0, n)


def coerce_flag(value, knob: str = "checker_sharded") -> bool | None:
    """Tolerant bool knob coercion: None/'' unset; bools and 0/1 pass;
    yes/no/true/false/on/off strings work; garbage warns and reads as
    unset (the env/ladder default then applies)."""
    if value is None or value == "":
        return None
    if isinstance(value, bool):
        return value
    if isinstance(value, (int, float)) and value in (0, 1):
        return bool(value)
    if isinstance(value, str):
        s = value.strip().lower()
        if s in ("1", "true", "yes", "on"):
            return True
        if s in ("0", "false", "no", "off"):
            return False
    logger.warning("ignoring malformed %s=%r (want a bool)", knob, value)
    return None


def sharding_knobs(test, opts) -> tuple:
    """The per-run sharding knob pair ``(checker_sharded flag,
    mesh_devices cap)`` from a checker's (test, opts), tolerantly
    coerced, opts taking precedence over the test map — the ONE reading
    LinearizableChecker and IndependentChecker share (True forces the
    sharded path, False disables it, None = env default + cost model)."""
    tmap = test if isinstance(test, dict) else {}
    flag = coerce_flag(opts.get("checker_sharded",
                                tmap.get("checker_sharded")))
    devices = coerce_devices(opts.get("mesh_devices",
                                      tmap.get("mesh_devices")))
    return flag, devices


def mesh_devices_limit() -> int | None:
    """The ``JEPSEN_TPU_MESH_DEVICES`` env cap on mesh width, tolerantly
    coerced (garbage warns and reads as unset, like the interpreter's
    knob layer). 0/1 effectively disables sharding; None = no cap."""
    import os
    return coerce_devices(os.environ.get("JEPSEN_TPU_MESH_DEVICES"),
                          knob="JEPSEN_TPU_MESH_DEVICES")


# ---------------------------------------------------------------------------
# Device health + the elastic mesh shrink path
# (doc/robustness.md "Resumable checks and the elastic mesh")
# ---------------------------------------------------------------------------

_HEALTH_LOCK = threading.Lock()
_FAILED_DEVICES: set[int] = set()

# mesh widths below this bottom out the shrink ladder (the checker then
# demotes to the single-device rungs); a 1-wide "mesh" is no mesh at all
DEFAULT_MESH_MIN_DEVICES = 2


def mark_device_failed(device_id: int) -> None:
    """Records a device as unhealthy: ``auto_mesh`` (and therefore
    every future sharded dispatch) builds over the survivors until
    :func:`reset_device_health`."""
    with _HEALTH_LOCK:
        if device_id in _FAILED_DEVICES:
            return
        _FAILED_DEVICES.add(device_id)
    logger.warning("device %d marked unhealthy; future meshes exclude it",
                   device_id)


def failed_device_ids() -> frozenset:
    with _HEALTH_LOCK:
        return frozenset(_FAILED_DEVICES)


def reset_device_health() -> None:
    """Clears the failed-device set — for tests, and for operators who
    fixed the accelerator (mirrors BackendLadder.reset)."""
    with _HEALTH_LOCK:
        _FAILED_DEVICES.clear()


def mesh_min_devices(value=None) -> int:
    """The shrink ladder's floor: the smallest mesh width worth keeping
    sharded (below it the checker demotes to single-device). Test-map
    knob ``mesh_min_devices`` (``value``), env twin
    ``JEPSEN_TPU_MESH_MIN_DEVICES``, default
    :data:`DEFAULT_MESH_MIN_DEVICES`; never below 2."""
    import os
    n = coerce_devices(value, knob="mesh_min_devices")
    if n is None:
        n = coerce_devices(os.environ.get("JEPSEN_TPU_MESH_MIN_DEVICES"),
                           knob="JEPSEN_TPU_MESH_MIN_DEVICES")
    if n is None:
        n = DEFAULT_MESH_MIN_DEVICES
    return max(2, n)


def _failed_ids_from_exc(exc, known_ids) -> list[int]:
    """Best-effort device attribution for a dispatch failure: device
    ids named in the exception text (``device 3``, ``TPU_5``, ...)
    that exist on this backend. Empty when the error names nothing —
    the shrink path then halves conservatively instead of guessing."""
    if exc is None:
        return []
    import re
    s = f"{type(exc).__name__}: {exc}"
    ids = set()
    for m in re.finditer(r"(?:device|TPU|tpu)[ _:#]*(\d+)", s):
        ids.add(int(m.group(1)))
    return sorted(i for i in ids if i in known_ids)


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def shrink_mesh(mesh, exc=None, min_devices: int | None = None,
                axis: str = "keys"):
    """The surviving mesh after a sharded-dispatch failure, or None
    when shrink bottoms out (fewer healthy devices than the
    ``mesh_min_devices`` floor — the caller demotes to single-device).

    Attribution: device ids named in ``exc`` are marked unhealthy; an
    unattributable failure (most collective errors name nothing)
    conservatively halves the width instead — either way the rebuilt
    mesh is strictly narrower than ``mesh``, so repeated shrinks
    terminate. Widths stay powers of two (the compile caches and the
    cost model's per-width EWMA rates both key on width, so a sparse
    width set keeps them warm). Counts ``mesh_shrink_total{from,to}``."""
    import jax
    cur = list(mesh.devices.flat)
    n_from = len(cur)
    try:
        all_devs = jax.devices()
    except Exception:  # noqa: BLE001 — backend gone entirely
        return None
    named = _failed_ids_from_exc(exc, {d.id for d in all_devs})
    for i in named:
        mark_device_failed(i)
    failed = failed_device_ids()
    healthy = [d for d in all_devs if d.id not in failed]
    if named and any(d.id in named for d in cur):
        # the error named the casualty: keep every survivor it allows
        target = _pow2_floor(min(len(healthy), n_from))
    else:
        # unattributable: drop half the lanes rather than guess wrong
        target = _pow2_floor(max(1, n_from // 2))
    if target >= n_from:
        target = _pow2_floor(max(1, n_from // 2))
    floor = mesh_min_devices(min_devices)
    if target < floor or len(healthy) < target:
        logger.warning("mesh shrink bottomed out (%d healthy, floor %d); "
                       "demoting to single-device", len(healthy), floor)
        return None
    new = auto_mesh(target, axis=axis)
    if new is None or int(new.devices.size) >= n_from:
        return None
    from jepsen_tpu import telemetry
    reg = telemetry.get_registry()
    if reg.enabled:
        reg.counter("mesh_shrink_total",
                    "elastic mesh shrinks after sharded-dispatch "
                    "failures, by width transition",
                    labels=("from", "to")).inc(
            **{"from": str(n_from), "to": str(int(new.devices.size))})
    from jepsen_tpu import trace as trace_mod
    trace_mod.get_tracer().instant(
        trace_mod.TRACK_LADDER, "mesh-shrink",
        args={"from": n_from, "to": int(new.devices.size),
              "error": type(exc).__name__ if exc is not None else None})
    logger.warning("mesh shrunk %d -> %d devices after dispatch failure "
                   "(%s)", n_from, int(new.devices.size),
                   f"{type(exc).__name__}" if exc is not None else
                   "unattributed")
    return new


def probe_device(device) -> bool:
    """One tiny H2D+D2H round trip on a single device — the heal
    probe. True means the device answered; False (any failure) means
    it stays on the unhealthy list."""
    try:
        import jax
        jax.device_get(jax.device_put(np.zeros(8, np.float32), device))
        return True
    except Exception:  # noqa: BLE001 — an unhealable device is just unhealed
        return False


def regrow_mesh(axis: str = "keys", probe=probe_device):
    """The elastic mesh's heal path: re-probe every device marked
    unhealthy, clear the ones that answer, and return the regrown mesh
    — or None when nothing healed (or healing didn't widen a
    power-of-two step, so the working width is unchanged).

    The twin of :func:`shrink_mesh`: shrink reacts to a dispatch
    failure, regrow reacts to the fleet scheduler's periodic heal probe
    (doc/robustness.md "The elastic mesh"). Widths stay powers of two
    for the same reason shrink's do — compile caches and the per-width
    rate EWMAs key on width. Counts ``mesh_regrow_total{from,to}``."""
    import jax
    failed = failed_device_ids()
    if not failed:
        return None
    try:
        all_devs = jax.devices()
    except Exception:  # noqa: BLE001 — backend gone entirely
        return None
    n_from = _pow2_floor(max(1, len(all_devs) - len(failed)))
    healed = [d.id for d in all_devs
              if d.id in failed and probe(d)]
    if not healed:
        return None
    with _HEALTH_LOCK:
        for i in healed:
            _FAILED_DEVICES.discard(i)
    still_failed = failed_device_ids()
    n_to = _pow2_floor(max(1, len(all_devs) - len(still_failed)))
    if n_to <= n_from or n_to < 2:
        return None
    new = auto_mesh(n_to, axis=axis)
    if new is None:
        return None
    from jepsen_tpu import telemetry
    reg = telemetry.get_registry()
    if reg.enabled:
        reg.counter("mesh_regrow_total",
                    "elastic mesh regrows after device heal probes, "
                    "by width transition",
                    labels=("from", "to")).inc(
            **{"from": str(n_from), "to": str(int(new.devices.size))})
    from jepsen_tpu import trace as trace_mod
    trace_mod.get_tracer().instant(
        trace_mod.TRACK_LADDER, "mesh-regrow",
        args={"from": n_from, "to": int(new.devices.size),
              "healed": healed})
    logger.info("mesh regrown %d -> %d devices (healed: %s)",
                n_from, int(new.devices.size), healed)
    return new


def auto_mesh(n_devices: int | None = None, axis: str = "keys"):
    """The cached 1-D mesh a sharded checker dispatch should run over,
    or None when fewer than 2 devices would participate. ``n_devices``
    caps the width (a test-map ``mesh_devices`` knob); the
    ``JEPSEN_TPU_MESH_DEVICES`` env var caps it globally; devices
    marked unhealthy (:func:`mark_device_failed` — the elastic shrink
    path) are excluded. Returning the SAME Mesh object per width keeps
    jitlin's mesh-keyed compile caches warm across dispatches."""
    import jax
    try:
        devs = jax.devices()
    except Exception:  # noqa: BLE001 — no backend: no mesh
        return None
    failed = failed_device_ids()
    if failed:
        devs = [d for d in devs if d.id not in failed]
    n = len(devs)
    if n_devices is not None:
        n = min(n, int(n_devices))
    limit = mesh_devices_limit()
    if limit is not None:
        n = min(n, limit)
    if n < 2:
        return None
    key = (n, axis)
    mesh = _MESH_CACHE.get(key)
    if mesh is None or list(mesh.devices.flat) != devs[:n]:
        from jax.sharding import Mesh
        mesh = Mesh(np.array(devs[:n]), (axis,))
        _MESH_CACHE[key] = mesh
    return mesh


def sharded_enabled() -> bool:
    """Is the sharded checker rung enabled? ``JEPSEN_TPU_SHARDED``
    (default on); the test-map ``checker_sharded`` knob overrides per
    run (checker/linearizable.py coerces it tolerantly)."""
    import os
    raw = os.environ.get("JEPSEN_TPU_SHARDED", "1").strip().lower()
    return raw not in ("0", "false", "no", "off", "")


def sharded_mesh_for(total_events: int, n_devices: int | None = None):
    """The mesh a sharded dispatch should use for ``total_events`` of
    work, or None: sharding disabled, <2 devices, or the cost model says
    the batch is too small to amortize mesh overhead (collective setup,
    divisibility padding, per-device dispatch) — small batches must not
    pay it (see pipeline.CostModel.mesh_route)."""
    if not sharded_enabled():
        return None
    mesh = auto_mesh(n_devices)
    if mesh is None:
        return None
    from jepsen_tpu.parallel import pipeline
    if not pipeline.mesh_route(total_events, int(mesh.devices.size)):
        return None
    return mesh


def shard_leading(mesh, *arrays):
    """Places arrays with their leading axis sharded over the mesh."""
    return shard_chunked(mesh, list(arrays), axis=0)


def shard_chunked(mesh, arrays, axis: int = 0):
    """Per-device transfer lanes: splits each array into contiguous
    per-device blocks along ``axis`` and stages each block onto its own
    device — every ``device_put`` issues that lane's H2D copy
    immediately and asynchronously, so the eight lanes' staging overlaps
    each other AND any in-flight compute (the DispatchPipeline overlap
    discipline, per device) — then assembles the global sharded array
    the shard_map kernels consume without a resharding copy. The sharded
    axis must be a device multiple; jitlin's planner guarantees that by
    padding (never by silently dropping the sharding)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    devs = list(mesh.devices.flat)
    nd = len(devs)
    out = []
    for a in arrays:
        a = np.asarray(a)
        if a.shape[axis] % nd:
            raise ValueError(
                f"axis {axis} length {a.shape[axis]} not divisible by "
                f"{nd} mesh devices — pad upstream (jitlin._matrix_plan /"
                f" parallel.pad_to_multiple)")
        spec = [None] * a.ndim
        spec[axis] = mesh.axis_names[0]
        sharding = NamedSharding(mesh, P(*spec))
        blocks = np.split(a, nd, axis=axis)
        parts = [jax.device_put(b, d) for b, d in zip(blocks, devs)]
        out.append(jax.make_array_from_single_device_arrays(
            a.shape, sharding, parts))
    return out


def pad_to_multiple(batch: dict, multiple: int) -> tuple[dict, int]:
    """Pads the leading (batch) axis of every array in the event batch to a
    multiple of `multiple` with EV_NOOP events. Returns (batch, real_B)."""
    from jepsen_tpu.ops.jitlin import EV_NOOP
    B = batch["kind"].shape[0]
    rem = (-B) % multiple
    if rem == 0:
        return batch, B
    out = {}
    for k, v in batch.items():
        if not isinstance(v, np.ndarray):
            out[k] = v
            continue
        pad_shape = (rem,) + v.shape[1:]
        fill = EV_NOOP if k == "kind" else 0
        out[k] = np.concatenate([v, np.full(pad_shape, fill, v.dtype)])
    return out, B


_DEFAULT_KERNEL = None

# How the most recent batch_check on THIS thread settled: "device"
# (single-device matrix/scan kernels), "mesh" (the shard_map multi-device
# path), or "cpu" (the auto-routed native/Python lane).
# Thread-local — Compose runs checkers concurrently under bounded_pmap,
# and a module global would let one thread's route mislabel another's
# results.
_ROUTE = threading.local()


def last_route() -> str:
    """The lane the calling thread's most recent batch_check took."""
    return getattr(_ROUTE, "value", "device")


def _default_kernel():
    """One shared default JitLinKernel — its compile cache must survive
    across batch_check calls (a fresh instance per call would re-jit the
    vmapped kernel every time)."""
    global _DEFAULT_KERNEL
    if _DEFAULT_KERNEL is None:
        from jepsen_tpu.ops.jitlin import JitLinKernel
        _DEFAULT_KERNEL = JitLinKernel()
    return _DEFAULT_KERNEL


def batch_check(streams: Sequence, capacity: int = 256, mesh=None,
                step_ids=None, init_state: int = 0, kernel=None,
                accelerator: str = "device", mesh_devices: int | None = None):
    """Checks a batch of per-key event streams, sharded across a device
    mesh when one is available. The single batching implementation —
    JitLinKernel.check/check_batch delegate here.

    Dispatch prefers the key-batched transfer-matrix kernel
    (jitlin.matrix_check_batch) when the whole batch fits its regime —
    all keys advance together in MXU matmuls instead of a latency-bound
    vmapped event scan. With a mesh the matrix path is still taken: its
    chunk axis is sharded across devices (matrix_check_batch handles the
    divisibility bump). The scan serves as the fallback for keys the
    matrix pass leaves undecided (not-alive or inexact).

    ``accelerator``: "device" (default — the historical behavior),
    "cpu" (the exact native/Python lane, bounded-thread-parallel over
    keys), or "auto" — consult the round-trip cost model
    (parallel.pipeline.CostModel) and take the CPU lane when it beats
    the device's dispatch-latency floor (small batches on tunneled
    chips). The thread-local ``last_route()`` records which lane
    settled for the calling thread ("cpu" / "device" / "mesh").
    ``mesh_devices`` caps auto-detected mesh width (the test-map knob;
    pass ``mesh=False`` to force single-device, as the multi-process
    path does).

    Returns [(alive, died_event, overflow, peak)] per stream (real keys
    only; padding keys are dropped).
    """
    import jax
    from jepsen_tpu.ops.jitlin import (
        EV_RETURN, MATRIX_MAX_ELEMS, MATRIX_MAX_SLOTS, MATRIX_MAX_STATES,
        MATRIX_MIN_RETURNS, MATRIX_SUB_KEYS, _bucket, matrix_check_batch)

    if kernel is None:
        if step_ids is None and init_state == 0:
            kernel = _default_kernel()
        else:
            from jepsen_tpu.ops.jitlin import JitLinKernel
            kernel = JitLinKernel(step_ids=step_ids, init_state=init_state)
    streams = list(streams)
    _ROUTE.value = "device"
    # an explicit mesh is an operator force (checker_sharded: True) —
    # the auto CPU route must not silently override it
    explicit_mesh = mesh is not None and mesh is not False
    if accelerator == "cpu" or (accelerator == "auto"
                                and not explicit_mesh):
        cpu = _cpu_batch_maybe(streams, kernel,
                               force=(accelerator == "cpu"))
        if cpu is not None:
            _ROUTE.value = "cpu"
            return cpu
    # interned-state count selects the exact dense-table kernel when the
    # configuration space 2^S x V is small (jitlin._build_dense_step);
    # every stream must carry an intern table, else a stream with
    # un-interned ids would be misencoded by the dense table
    if all(getattr(s, "intern", None) is not None for s in streams):
        n_states = max(len(s.intern) for s in streams)
    else:
        n_states = None

    # mesh=False forces single-device local execution — the multi-process
    # path (distributed.batch_check_distributed) splits keys BY PROCESS
    # and must not let auto-detection grab the process-spanning global
    # mesh (a process can only address its own devices' shards)
    total_events = sum(len(s.kind) for s in streams)
    if mesh is False:
        mesh = None
    elif mesh is None:
        # cost-gated: a small batch must not pay mesh overhead
        # (collective setup, divisibility padding) — the per-device-count
        # rate model routes it to one device (doc/performance.md);
        # ``mesh_devices`` (the test-map knob) caps the width
        mesh = sharded_mesh_for(total_events, mesh_devices)
    if mesh is not None:
        _ROUTE.value = "mesh"

    S_all = max(max(1, s.n_slots) for s in streams)
    if n_states is not None and S_all <= MATRIX_MAX_SLOTS \
            and n_states <= MATRIX_MAX_STATES:
        mv = (1 << S_all) * _bucket(n_states, floor=8)
        total_returns = sum(int((np.asarray(s.kind) == EV_RETURN).sum())
                            for s in streams)
        # single-device batches split into MATRIX_SUB_KEYS dispatches, so
        # the element budget binds per sub-batch, not the whole key set.
        # A mesh pads keys to a device multiple and holds B/nd per device
        sub = (-(-len(streams) // int(mesh.devices.size))
               if mesh is not None
               else min(len(streams), MATRIX_SUB_KEYS))
        if total_returns >= MATRIX_MIN_RETURNS \
                and sub * mv * mv <= MATRIX_MAX_ELEMS:
            # matrix_check_batch feeds the per-device-count rate model
            # itself (every caller benefits, not just this one)
            results = matrix_check_batch(
                streams, step_ids=kernel.step_ids,
                init_state=kernel.init_state, num_states=n_states,
                mesh=mesh)
            undecided = [i for i, r in enumerate(results)
                         if not r[0] or r[2]]
            if undecided:
                redo = _scan_batch([streams[i] for i in undecided],
                                   capacity, mesh, kernel, n_states)
                results = list(results)
                for i, r in zip(undecided, redo):
                    results[i] = r
            return results

    return _scan_batch(streams, capacity, mesh, kernel, n_states)


def _cpu_batch_maybe(streams, kernel, force: bool = False):
    """The C++/CPU lane for ``accelerator=auto``: when the round-trip
    cost model predicts the device's dispatch-latency floor dominates
    (sub-128-key ``independent`` batches on tunneled chips), checks the
    keys exactly on host — native C++ first (ctypes releases the GIL, so
    bounded_pmap runs keys genuinely in parallel), Python stream search
    as the fallback. Returns None when the device lane should run
    (model says so, or the kernel's spec has no Python twin here).
    Measured CPU throughput feeds back into the cost model
    (pipeline.observe_cpu_rate) so routing tracks the actual host."""
    import time

    from jepsen_tpu.parallel import pipeline

    # the host lane runs the CAS-register search (the Python twin
    # honors any init_state; the native C++ lane hardcodes init id 0) —
    # other specs keep the device lane, whose kernels are spec-generic.
    # The spec is recognized by its closure origin: cas_register_spec
    # builds a fresh step_ids per call, so identity against the shared
    # default is not enough (the checker builds its own spec instance).
    qn = getattr(kernel.step_ids, "__qualname__", "")
    if not qn.startswith("cas_register_spec."):
        if force:
            # an EXPLICIT cpu request that can't be honored must not
            # silently become a device dispatch
            logger.warning(
                "accelerator=cpu requested but kernel spec %r has no "
                "host twin in batch_check; using the device lane", qn)
        return None
    init_state = kernel.init_state
    total_events = sum(len(s.kind) for s in streams)
    if not force and pipeline.auto_route(total_events) != "cpu":
        return None
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.native import check_stream_native
    from jepsen_tpu.utils import bounded_pmap

    def one(stream):
        res = check_stream_native(stream) if init_state == 0 else None
        if res is None or res.valid == "unknown":
            res = check_stream(stream, init_state=init_state)
        return (res.valid is True, res.failed_event, False,
                res.configs_max)

    t0 = time.perf_counter()
    out = bounded_pmap(one, streams)
    pipeline.observe_cpu_rate(total_events, time.perf_counter() - t0)
    return out


def _scan_batch(streams, capacity, mesh, kernel, n_states):
    """The vmapped event-scan path (dense or sparse frontier kernel)."""
    import jax

    from jepsen_tpu.checker.linear_encode import pad_streams
    from jepsen_tpu.ops.jitlin import _bucket

    batch = pad_streams(streams, length=_bucket(max(len(s) for s in streams)))
    S = max(1, batch["n_slots"])
    if mesh is not None:
        n_dev = mesh.devices.size
        batch, real_b = pad_to_multiple(batch, n_dev)
        arrays = [batch["kind"], batch["slot"], batch["f"], batch["a"], batch["b"]]
        arrays = shard_leading(mesh, *arrays)
    else:
        real_b = batch["kind"].shape[0]
        arrays = [batch["kind"], batch["slot"], batch["f"], batch["a"], batch["b"]]

    fn = kernel._get(S, capacity, batched=True, num_states=n_states)
    alive, died, ovf, peak = fn(*arrays)
    # ONE batched host transfer: each np.asarray is a full tunnel
    # round-trip (~100 ms on remote-attached chips), so four sequential
    # syncs would quadruple the fixed cost of every batch check
    alive, died, ovf, peak = jax.device_get((alive, died, ovf, peak))
    return [(bool(alive[i]), int(died[i]), bool(ovf[i]), int(peak[i]))
            for i in range(real_b)]
