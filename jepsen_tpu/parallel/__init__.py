"""Device-mesh parallelism for the checker data plane.

The reference's 'distributed communication backend' is SSH fan-out
(SURVEY.md §5.8); ours is XLA collectives over a `jax.sharding.Mesh`. The
checker workloads are batch-parallel over keys (independent registers) and
graph-parallel over txn partitions, so the sharding story is:

* ``keys`` axis: per-key event tensors sharded over all devices; the
  jitlin kernel runs under vmap with inputs/outputs NamedSharding'd on the
  leading axis, so each device checks its shard of keys with zero
  cross-device traffic until the final verdict gather (ICI all-gather of
  B bools).
* SCC label propagation shards edges over devices and psums the label
  updates (see ops/scc.py) — collectives ride ICI on a pod.

Multi-host: ``parallel.distributed`` initializes ``jax.distributed``,
builds a process-spanning global mesh, places per-process edge shards
with make_array_from_process_local_data for the sharded trim (psum
crossing the process boundary), and splits independent key batches by
process with a verdict allgather. Exercised for real by
tests/test_distributed.py: two OS processes × 4 virtual CPU devices
form one 8-device mesh and run both paths end to end.
"""
from __future__ import annotations

import logging
import threading
from typing import Sequence

import numpy as np

logger = logging.getLogger("jepsen.parallel")


def devices():
    import jax
    return jax.devices()


def get_mesh(n_devices: int | None = None, axis: str = "keys"):
    """A 1-D mesh over available devices (jax.sharding.Mesh)."""
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def shard_leading(mesh, *arrays):
    """Places arrays with their leading axis sharded over the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    return [jax.device_put(a, sharding) for a in arrays]


def pad_to_multiple(batch: dict, multiple: int) -> tuple[dict, int]:
    """Pads the leading (batch) axis of every array in the event batch to a
    multiple of `multiple` with EV_NOOP events. Returns (batch, real_B)."""
    from jepsen_tpu.ops.jitlin import EV_NOOP
    B = batch["kind"].shape[0]
    rem = (-B) % multiple
    if rem == 0:
        return batch, B
    out = {}
    for k, v in batch.items():
        if not isinstance(v, np.ndarray):
            out[k] = v
            continue
        pad_shape = (rem,) + v.shape[1:]
        fill = EV_NOOP if k == "kind" else 0
        out[k] = np.concatenate([v, np.full(pad_shape, fill, v.dtype)])
    return out, B


_DEFAULT_KERNEL = None

# How the most recent batch_check on THIS thread settled: "device"
# (matrix/scan kernels) or "cpu" (the auto-routed native/Python lane).
# Thread-local — Compose runs checkers concurrently under bounded_pmap,
# and a module global would let one thread's route mislabel another's
# results.
_ROUTE = threading.local()


def last_route() -> str:
    """The lane the calling thread's most recent batch_check took."""
    return getattr(_ROUTE, "value", "device")


def _default_kernel():
    """One shared default JitLinKernel — its compile cache must survive
    across batch_check calls (a fresh instance per call would re-jit the
    vmapped kernel every time)."""
    global _DEFAULT_KERNEL
    if _DEFAULT_KERNEL is None:
        from jepsen_tpu.ops.jitlin import JitLinKernel
        _DEFAULT_KERNEL = JitLinKernel()
    return _DEFAULT_KERNEL


def batch_check(streams: Sequence, capacity: int = 256, mesh=None,
                step_ids=None, init_state: int = 0, kernel=None,
                accelerator: str = "device"):
    """Checks a batch of per-key event streams, sharded across a device
    mesh when one is available. The single batching implementation —
    JitLinKernel.check/check_batch delegate here.

    Dispatch prefers the key-batched transfer-matrix kernel
    (jitlin.matrix_check_batch) when the whole batch fits its regime —
    all keys advance together in MXU matmuls instead of a latency-bound
    vmapped event scan. With a mesh the matrix path is still taken: its
    chunk axis is sharded across devices (matrix_check_batch handles the
    divisibility bump). The scan serves as the fallback for keys the
    matrix pass leaves undecided (not-alive or inexact).

    ``accelerator``: "device" (default — the historical behavior),
    "cpu" (the exact native/Python lane, bounded-thread-parallel over
    keys), or "auto" — consult the round-trip cost model
    (parallel.pipeline.CostModel) and take the CPU lane when it beats
    the device's dispatch-latency floor (small batches on tunneled
    chips). The thread-local ``last_route()`` records which lane
    settled for the calling thread.

    Returns [(alive, died_event, overflow, peak)] per stream (real keys
    only; padding keys are dropped).
    """
    import jax
    from jepsen_tpu.ops.jitlin import (
        EV_RETURN, MATRIX_MAX_ELEMS, MATRIX_MAX_SLOTS, MATRIX_MAX_STATES,
        MATRIX_MIN_RETURNS, MATRIX_SUB_KEYS, _bucket, matrix_check_batch)

    if kernel is None:
        if step_ids is None and init_state == 0:
            kernel = _default_kernel()
        else:
            from jepsen_tpu.ops.jitlin import JitLinKernel
            kernel = JitLinKernel(step_ids=step_ids, init_state=init_state)
    streams = list(streams)
    _ROUTE.value = "device"
    if accelerator in ("cpu", "auto"):
        cpu = _cpu_batch_maybe(streams, kernel,
                               force=(accelerator == "cpu"))
        if cpu is not None:
            _ROUTE.value = "cpu"
            return cpu
    # interned-state count selects the exact dense-table kernel when the
    # configuration space 2^S x V is small (jitlin._build_dense_step);
    # every stream must carry an intern table, else a stream with
    # un-interned ids would be misencoded by the dense table
    if all(getattr(s, "intern", None) is not None for s in streams):
        n_states = max(len(s.intern) for s in streams)
    else:
        n_states = None

    # mesh=False forces single-device local execution — the multi-process
    # path (distributed.batch_check_distributed) splits keys BY PROCESS
    # and must not let auto-detection grab the process-spanning global
    # mesh (a process can only address its own devices' shards)
    if mesh is False:
        mesh = None
    elif mesh is None and len(jax.devices()) > 1:
        mesh = get_mesh()

    S_all = max(max(1, s.n_slots) for s in streams)
    if n_states is not None and S_all <= MATRIX_MAX_SLOTS \
            and n_states <= MATRIX_MAX_STATES:
        mv = (1 << S_all) * _bucket(n_states, floor=8)
        total_returns = sum(int((np.asarray(s.kind) == EV_RETURN).sum())
                            for s in streams)
        # single-device batches split into MATRIX_SUB_KEYS dispatches, so
        # the element budget binds per sub-batch, not the whole key set
        sub = (len(streams) if mesh is not None
               else min(len(streams), MATRIX_SUB_KEYS))
        if total_returns >= MATRIX_MIN_RETURNS \
                and sub * mv * mv <= MATRIX_MAX_ELEMS:
            results = matrix_check_batch(
                streams, step_ids=kernel.step_ids,
                init_state=kernel.init_state, num_states=n_states,
                mesh=mesh)
            undecided = [i for i, r in enumerate(results)
                         if not r[0] or r[2]]
            if undecided:
                redo = _scan_batch([streams[i] for i in undecided],
                                   capacity, mesh, kernel, n_states)
                results = list(results)
                for i, r in zip(undecided, redo):
                    results[i] = r
            return results

    return _scan_batch(streams, capacity, mesh, kernel, n_states)


def _cpu_batch_maybe(streams, kernel, force: bool = False):
    """The C++/CPU lane for ``accelerator=auto``: when the round-trip
    cost model predicts the device's dispatch-latency floor dominates
    (sub-128-key ``independent`` batches on tunneled chips), checks the
    keys exactly on host — native C++ first (ctypes releases the GIL, so
    bounded_pmap runs keys genuinely in parallel), Python stream search
    as the fallback. Returns None when the device lane should run
    (model says so, or the kernel's spec has no Python twin here).
    Measured CPU throughput feeds back into the cost model
    (pipeline.observe_cpu_rate) so routing tracks the actual host."""
    import time

    from jepsen_tpu.parallel import pipeline

    # the host lane runs the CAS-register search (the Python twin
    # honors any init_state; the native C++ lane hardcodes init id 0) —
    # other specs keep the device lane, whose kernels are spec-generic.
    # The spec is recognized by its closure origin: cas_register_spec
    # builds a fresh step_ids per call, so identity against the shared
    # default is not enough (the checker builds its own spec instance).
    qn = getattr(kernel.step_ids, "__qualname__", "")
    if not qn.startswith("cas_register_spec."):
        if force:
            # an EXPLICIT cpu request that can't be honored must not
            # silently become a device dispatch
            logger.warning(
                "accelerator=cpu requested but kernel spec %r has no "
                "host twin in batch_check; using the device lane", qn)
        return None
    init_state = kernel.init_state
    total_events = sum(len(s.kind) for s in streams)
    if not force and pipeline.auto_route(total_events) != "cpu":
        return None
    from jepsen_tpu.checker.linear_cpu import check_stream
    from jepsen_tpu.native import check_stream_native
    from jepsen_tpu.utils import bounded_pmap

    def one(stream):
        res = check_stream_native(stream) if init_state == 0 else None
        if res is None or res.valid == "unknown":
            res = check_stream(stream, init_state=init_state)
        return (res.valid is True, res.failed_event, False,
                res.configs_max)

    t0 = time.perf_counter()
    out = bounded_pmap(one, streams)
    pipeline.observe_cpu_rate(total_events, time.perf_counter() - t0)
    return out


def _scan_batch(streams, capacity, mesh, kernel, n_states):
    """The vmapped event-scan path (dense or sparse frontier kernel)."""
    import jax

    from jepsen_tpu.checker.linear_encode import pad_streams
    from jepsen_tpu.ops.jitlin import _bucket

    batch = pad_streams(streams, length=_bucket(max(len(s) for s in streams)))
    S = max(1, batch["n_slots"])
    if mesh is not None:
        n_dev = mesh.devices.size
        batch, real_b = pad_to_multiple(batch, n_dev)
        arrays = [batch["kind"], batch["slot"], batch["f"], batch["a"], batch["b"]]
        arrays = shard_leading(mesh, *arrays)
    else:
        real_b = batch["kind"].shape[0]
        arrays = [batch["kind"], batch["slot"], batch["f"], batch["a"], batch["b"]]

    fn = kernel._get(S, capacity, batched=True, num_states=n_states)
    alive, died, ovf, peak = fn(*arrays)
    # ONE batched host transfer: each np.asarray is a full tunnel
    # round-trip (~100 ms on remote-attached chips), so four sequential
    # syncs would quadruple the fixed cost of every batch check
    alive, died, ovf, peak = jax.device_get((alive, died, ovf, peak))
    return [(bool(alive[i]), int(died[i]), bool(ovf[i]), int(peak[i]))
            for i in range(real_b)]
