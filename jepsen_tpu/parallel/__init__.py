"""Device-mesh parallelism for the checker data plane.

The reference's 'distributed communication backend' is SSH fan-out
(SURVEY.md §5.8); ours is XLA collectives over a `jax.sharding.Mesh`. The
checker workloads are batch-parallel over keys (independent registers) and
graph-parallel over txn partitions, so the sharding story is:

* ``keys`` axis: per-key event tensors sharded over all devices; the
  jitlin kernel runs under vmap with inputs/outputs NamedSharding'd on the
  leading axis, so each device checks its shard of keys with zero
  cross-device traffic until the final verdict gather (ICI all-gather of
  B bools).
* SCC label propagation shards edges over devices and psums the label
  updates (see ops/scc.py) — collectives ride ICI on a pod.

Multi-host: ``parallel.distributed`` initializes ``jax.distributed``,
builds a process-spanning global mesh, places per-process edge shards
with make_array_from_process_local_data for the sharded trim (psum
crossing the process boundary), and splits independent key batches by
process with a verdict allgather. Exercised for real by
tests/test_distributed.py: two OS processes × 4 virtual CPU devices
form one 8-device mesh and run both paths end to end.
"""
from __future__ import annotations

import logging
from typing import Sequence

import numpy as np

logger = logging.getLogger("jepsen.parallel")


def devices():
    import jax
    return jax.devices()


def get_mesh(n_devices: int | None = None, axis: str = "keys"):
    """A 1-D mesh over available devices (jax.sharding.Mesh)."""
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def shard_leading(mesh, *arrays):
    """Places arrays with their leading axis sharded over the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    return [jax.device_put(a, sharding) for a in arrays]


def pad_to_multiple(batch: dict, multiple: int) -> tuple[dict, int]:
    """Pads the leading (batch) axis of every array in the event batch to a
    multiple of `multiple` with EV_NOOP events. Returns (batch, real_B)."""
    from jepsen_tpu.ops.jitlin import EV_NOOP
    B = batch["kind"].shape[0]
    rem = (-B) % multiple
    if rem == 0:
        return batch, B
    out = {}
    for k, v in batch.items():
        if not isinstance(v, np.ndarray):
            out[k] = v
            continue
        pad_shape = (rem,) + v.shape[1:]
        fill = EV_NOOP if k == "kind" else 0
        out[k] = np.concatenate([v, np.full(pad_shape, fill, v.dtype)])
    return out, B


_DEFAULT_KERNEL = None


def _default_kernel():
    """One shared default JitLinKernel — its compile cache must survive
    across batch_check calls (a fresh instance per call would re-jit the
    vmapped kernel every time)."""
    global _DEFAULT_KERNEL
    if _DEFAULT_KERNEL is None:
        from jepsen_tpu.ops.jitlin import JitLinKernel
        _DEFAULT_KERNEL = JitLinKernel()
    return _DEFAULT_KERNEL


def batch_check(streams: Sequence, capacity: int = 256, mesh=None,
                step_ids=None, init_state: int = 0, kernel=None):
    """Checks a batch of per-key event streams, sharded across a device
    mesh when one is available. The single batching implementation —
    JitLinKernel.check/check_batch delegate here.

    Dispatch prefers the key-batched transfer-matrix kernel
    (jitlin.matrix_check_batch) when the whole batch fits its regime —
    all keys advance together in MXU matmuls instead of a latency-bound
    vmapped event scan. With a mesh the matrix path is still taken: its
    chunk axis is sharded across devices (matrix_check_batch handles the
    divisibility bump). The scan serves as the fallback for keys the
    matrix pass leaves undecided (not-alive or inexact).

    Returns [(alive, died_event, overflow, peak)] per stream (real keys
    only; padding keys are dropped).
    """
    import jax
    from jepsen_tpu.ops.jitlin import (
        EV_RETURN, MATRIX_MAX_ELEMS, MATRIX_MAX_SLOTS, MATRIX_MAX_STATES,
        MATRIX_MIN_RETURNS, MATRIX_SUB_KEYS, _bucket, matrix_check_batch)

    if kernel is None:
        if step_ids is None and init_state == 0:
            kernel = _default_kernel()
        else:
            from jepsen_tpu.ops.jitlin import JitLinKernel
            kernel = JitLinKernel(step_ids=step_ids, init_state=init_state)
    streams = list(streams)
    # interned-state count selects the exact dense-table kernel when the
    # configuration space 2^S x V is small (jitlin._build_dense_step);
    # every stream must carry an intern table, else a stream with
    # un-interned ids would be misencoded by the dense table
    if all(getattr(s, "intern", None) is not None for s in streams):
        n_states = max(len(s.intern) for s in streams)
    else:
        n_states = None

    # mesh=False forces single-device local execution — the multi-process
    # path (distributed.batch_check_distributed) splits keys BY PROCESS
    # and must not let auto-detection grab the process-spanning global
    # mesh (a process can only address its own devices' shards)
    if mesh is False:
        mesh = None
    elif mesh is None and len(jax.devices()) > 1:
        mesh = get_mesh()

    S_all = max(max(1, s.n_slots) for s in streams)
    if n_states is not None and S_all <= MATRIX_MAX_SLOTS \
            and n_states <= MATRIX_MAX_STATES:
        mv = (1 << S_all) * _bucket(n_states, floor=8)
        total_returns = sum(int((np.asarray(s.kind) == EV_RETURN).sum())
                            for s in streams)
        # single-device batches split into MATRIX_SUB_KEYS dispatches, so
        # the element budget binds per sub-batch, not the whole key set
        sub = (len(streams) if mesh is not None
               else min(len(streams), MATRIX_SUB_KEYS))
        if total_returns >= MATRIX_MIN_RETURNS \
                and sub * mv * mv <= MATRIX_MAX_ELEMS:
            results = matrix_check_batch(
                streams, step_ids=kernel.step_ids,
                init_state=kernel.init_state, num_states=n_states,
                mesh=mesh)
            undecided = [i for i, r in enumerate(results)
                         if not r[0] or r[2]]
            if undecided:
                redo = _scan_batch([streams[i] for i in undecided],
                                   capacity, mesh, kernel, n_states)
                results = list(results)
                for i, r in zip(undecided, redo):
                    results[i] = r
            return results

    return _scan_batch(streams, capacity, mesh, kernel, n_states)


def _scan_batch(streams, capacity, mesh, kernel, n_states):
    """The vmapped event-scan path (dense or sparse frontier kernel)."""
    import jax

    from jepsen_tpu.checker.linear_encode import pad_streams
    from jepsen_tpu.ops.jitlin import _bucket

    batch = pad_streams(streams, length=_bucket(max(len(s) for s in streams)))
    S = max(1, batch["n_slots"])
    if mesh is not None:
        n_dev = mesh.devices.size
        batch, real_b = pad_to_multiple(batch, n_dev)
        arrays = [batch["kind"], batch["slot"], batch["f"], batch["a"], batch["b"]]
        arrays = shard_leading(mesh, *arrays)
    else:
        real_b = batch["kind"].shape[0]
        arrays = [batch["kind"], batch["slot"], batch["f"], batch["a"], batch["b"]]

    fn = kernel._get(S, capacity, batched=True, num_states=n_states)
    alive, died, ovf, peak = fn(*arrays)
    # ONE batched host transfer: each np.asarray is a full tunnel
    # round-trip (~100 ms on remote-attached chips), so four sequential
    # syncs would quadruple the fixed cost of every batch check
    alive, died, ovf, peak = jax.device_get((alive, died, ovf, peak))
    return [(bool(alive[i]), int(died[i]), bool(ovf[i]), int(peak[i]))
            for i in range(real_b)]
