"""jepsen_tpu: a TPU-native distributed-systems-testing framework.

Capability-equivalent to Jepsen (reference: /root/reference/jepsen): a test is
a plain dict; a control node drives N db nodes over SSH; a purely-functional
generator schedules concurrent client ops; a nemesis injects faults; the
recorded history is verified by checkers. Unlike the reference (Clojure +
JVM-hosted knossos/elle searches), the compute-bound checkers here run as
batched JAX/XLA kernels on TPU, with CPU implementations kept as the
differential-testing oracle.

Layer map (mirrors SURVEY.md §1):
  L0 control/        remote execution (Remote protocol: ssh/docker/k8s/dummy)
  L1 db.py, os_setup/, net.py   environment automation
  L2 core.py         orchestrator (run, analyze)
  L3 nemesis/        fault injection
  L4 generator/      pure scheduling DSL + threaded interpreter
  L5 client.py       DB client protocol
  L6 checker/, models/, ops/    analysis (TPU hot path)
  L7 store.py, web.py, cli.py   persistence / reporting / CLI
"""

__version__ = "0.1.0"
