"""MongoDB test suite (reference: the mongodb-*/ suites in
jaydenwen123/jepsen — replica-set mongod clusters probed with
majority-write/majority-read registers).

DB automation installs mongod on each node, starts it with a shared
replica-set name, and initiates the replica set from node 1 with every
node as a member (the reference's mongodb/core.clj bring-up). The
client needs pymongo (not bundled): registers are per-key documents
updated with majority write concern and read with linearizable read
concern; cas is a conditional find_one_and_update, so a lost race is a
definite ``fail``. Without pymongo the suite runs with ``--fake``
in-memory doubles.
"""
from __future__ import annotations

import json
import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)

logger = logging.getLogger("jepsen.mongodb")

PORT = 27017
RS_NAME = "jepsen"
DIR = "/opt/mongo"
DATA_DIR = f"{DIR}/data"
LOG_FILE = f"{DIR}/mongod.log"
PIDFILE = f"{DIR}/mongod.pid"


class MongoDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.Primary,
              db_mod.LogFiles):
    """Replica-set mongod lifecycle (reference mongodb/core.clj).

    ``storage_engine`` covers the reference's per-engine suite variants:
    the mongodb-rocks/ suite is this deployment with the rocksdb engine,
    mongodb-smartos/ pairs the default engine with the SmartOS OS layer
    (os_setup.SmartOS)."""

    def __init__(self, storage_engine: str | None = None):
        self.storage_engine = storage_engine

    def setup(self, test, node):
        logger.info("%s: installing mongod", node)
        from jepsen_tpu import os_setup
        if isinstance(test.get("os"), os_setup.SmartOS):
            # the mongodb-smartos variant: pkgin, not apt
            control.exec_("pkgin", "-y", "install", "mongodb")
        else:
            os_setup.install(["mongodb-org-server", "mongodb-mongosh"])
        cu.mkdir(DATA_DIR)
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node)
        # replica-set initiation barriers on every member being up
        from jepsen_tpu import core
        core.synchronize(test)
        if node == (test.get("nodes") or [node])[0]:
            members = [{"_id": i, "host": f"{n}:{PORT}"}
                       for i, n in enumerate(test.get("nodes") or [])]
            conf = json.dumps({"_id": RS_NAME, "members": members})
            control.exec_(control.lit(
                f"mongosh --quiet --eval 'rs.initiate({conf})' "
                f"|| true"))

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DATA_DIR)
        cu.rm_rf(LOG_FILE)

    def start(self, test, node):
        args = ["--replSet", RS_NAME,
                "--dbpath", DATA_DIR,
                "--port", str(PORT),
                "--bind_ip_all"]
        if self.storage_engine:
            args += ["--storageEngine", self.storage_engine]
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            "mongod", *args)

    def kill(self, test, node):
        cu.stop_daemon("mongod", PIDFILE)
        cu.grepkill("mongod")

    def pause(self, test, node):
        cu.grepkill("mongod", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("mongod", sig="CONT")

    def primaries(self, test):
        return (test.get("nodes") or [])[:1]

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        return [LOG_FILE]


class MongoClient(Client):
    """Majority-write / linearizable-read register + set client.
    Requires pymongo; the suite's --fake mode runs without it."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node
        self.client = None

    def open(self, test, node):
        try:
            import pymongo
        except ImportError as e:
            raise RuntimeError(
                "pymongo is not installed; run this suite with --fake or "
                "install pymongo for a real cluster") from e
        c = MongoClient(self.timeout_s, node)
        ms = int(self.timeout_s * 1000)
        c.client = pymongo.MongoClient(
            host=node, port=PORT, replicaSet=RS_NAME,
            serverSelectionTimeoutMS=ms, socketTimeoutMS=ms,
            connectTimeoutMS=ms)
        return c

    def _coll(self, name="registers"):
        import pymongo
        from pymongo.read_concern import ReadConcern
        from pymongo.write_concern import WriteConcern
        return self.client.jepsen.get_collection(
            name,
            read_concern=ReadConcern("linearizable"),
            write_concern=WriteConcern("majority"),
            read_preference=pymongo.ReadPreference.PRIMARY)

    def setup(self, test):
        if test.get("transfer"):
            # seed the account pool (transfer.clj:137-146)
            for a in test.get("transfer_accounts") or []:
                self._coll("accts").update_one(
                    {"_id": a},
                    {"$setOnInsert": {
                        "balance": test.get("starting_balance", 10),
                        "pendingTxns": []}},
                    upsert=True)

    def _transfer_invoke(self, test, op):
        """The two-phase-commit transfer dance (transfer.clj:43-133):
        create a txn document, apply both $inc sides guarded on the txn
        not being pending on that account, mark applied, clear pending
        markers, mark done."""
        f, v = op.get("f"), op.get("value")
        accts, txns = self._coll("accts"), self._coll("txns")
        if f == "read":
            docs = accts.find({}, {"_id": 1, "balance": 1})
            return {**op, "type": "ok",
                    "value": {d["_id"]: d["balance"] for d in docs}}
        if f == "partial-read":
            docs = accts.find({"pendingTxns": {"$size": 0}},
                              {"_id": 1, "balance": 1})
            return {**op, "type": "ok",
                    "value": {d["_id"]: d["balance"] for d in docs}}
        if f == "transfer":
            frm, to, amount = v["from"], v["to"], v["amount"]
            tid = txns.insert_one(
                {"state": "pending", "from": frm, "to": to,
                 "amount": amount}).inserted_id
            accts.update_one({"_id": frm, "pendingTxns": {"$ne": tid}},
                             {"$inc": {"balance": -amount},
                              "$push": {"pendingTxns": tid}})
            accts.update_one({"_id": to, "pendingTxns": {"$ne": tid}},
                             {"$inc": {"balance": amount},
                              "$push": {"pendingTxns": tid}})
            txns.update_one({"_id": tid, "state": "pending"},
                            {"$set": {"state": "applied"}})
            accts.update_one({"_id": frm, "pendingTxns": tid},
                             {"$pull": {"pendingTxns": tid}})
            accts.update_one({"_id": to, "pendingTxns": tid},
                             {"$pull": {"pendingTxns": tid}})
            txns.update_one({"_id": tid, "state": "applied"},
                            {"$set": {"state": "done"}})
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    def invoke(self, test, op):
        import pymongo.errors
        f, v = op.get("f"), op.get("value")
        try:
            if test.get("transfer"):
                return self._transfer_invoke(test, op)
            if f == "add":
                self._coll("sets").update_one(
                    {"_id": v}, {"$set": {"_id": v}}, upsert=True)
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                elems = sorted(d["_id"] for d in self._coll("sets").find())
                return {**op, "type": "ok", "value": elems}
            if f == "read":
                k, _ = v
                doc = self._coll().find_one({"_id": k})
                return {**op, "type": "ok",
                        "value": [k, doc["v"] if doc else None]}
            if f == "write":
                k, val = v
                self._coll().update_one({"_id": k}, {"$set": {"v": val}},
                                        upsert=True)
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                doc = self._coll().find_one_and_update(
                    {"_id": k, "v": old}, {"$set": {"v": new}})
                return {**op, "type": "ok" if doc is not None else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except pymongo.errors.PyMongoError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["mongo", type(e).__name__]}

    def close(self, test):
        if self.client is not None:
            self.client.close()


class FakeTransferMongo(Client):
    """In-memory double for the transfer workload: transfers apply
    atomically under one lock, so the fake history is linearizable by
    construction and the Accounts-model check must pass."""

    def __init__(self, state=None):
        import threading
        self.state = state if state is not None else {
            "lock": threading.Lock(), "balances": {}}

    def open(self, test, node):
        return type(self)(self.state)

    def setup(self, test):
        with self.state["lock"]:
            for a in test.get("transfer_accounts") or []:
                self.state["balances"].setdefault(
                    a, test.get("starting_balance", 10))

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        with self.state["lock"]:
            balances = self.state["balances"]
            if f in ("read", "partial-read"):
                return {**op, "type": "ok", "value": dict(balances)}
            if f == "transfer":
                balances[v["from"]] -= v["amount"]
                balances[v["to"]] += v["amount"]
                return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": ["unknown-f", f]}


SUPPORTED_WORKLOADS = ("register", "set", "transfer")


def mongodb_test(opts_dict: dict | None = None) -> dict:
    o = dict(opts_dict or {})
    from jepsen_tpu.workloads import transfer

    def make_real(o):
        from jepsen_tpu import os_setup
        os_cls = (os_setup.SmartOS if o.get("os") == "smartos" else Debian)
        return {"db": MongoDB(o.get("storage_engine")),
                "client": MongoClient(), "os": os_cls()}

    fake_client = (FakeTransferMongo if o.get("workload") == "transfer"
                   else None)
    return build_suite_test(
        o, db_name="mongodb",
        supported_workloads=SUPPORTED_WORKLOADS, make_real=make_real,
        extra_workloads={"transfer": transfer.workload},
        fake_client=fake_client)


main_all = standard_test_all(mongodb_test, SUPPORTED_WORKLOADS,
                             name="jepsen-mongodb")

main = cli.single_test_cmd(
    standard_test_fn(mongodb_test, extra_keys=("storage_engine",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    # the shared --os option covers the smartos variant
                    # (a suite-local --os would collide with it)
                    extra=lambda p: p.add_argument(
                        "--storage-engine",
                        dest="storage_engine", default=None,
                        help="e.g. wiredTiger or rocksdb "
                             "(the mongodb-rocks variant)")),
    name="jepsen-mongodb")


if __name__ == "__main__":
    import sys
    sys.exit(main())
