"""RabbitMQ test suite (reference: rabbitmq/src/jepsen/rabbitmq.clj —
a mirrored durable queue under partitions, the analysis that first
demonstrated RabbitMQ losing acknowledged messages).

The client rides the bundled AMQP 0-9-1 wire implementation
(``_amqp.py``): enqueues publish persistent messages in publisher-
confirm mode and only report ``ok`` once the broker acks the confirm
(rabbitmq.clj:155-165); dequeues are ``basic.get`` + explicit ack,
with an empty queue a definite ``fail``; drain loops dequeue until
empty (rabbitmq.clj:105-117,167-172). Checked with total-queue
multiset algebra.

DB automation per rabbitmq.clj:24-101: install the server, share one
erlang cookie, stop_app/join_cluster/start_app every node onto node 1,
then mirror ``jepsen.``-prefixed queues across 3 nodes with ha-mode
"exactly" + automatic sync.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._amqp import AmqpConnection, AmqpError

logger = logging.getLogger("jepsen.rabbitmq")

PORT = 5672
QUEUE = "jepsen.queue"
COOKIE = "jepsen-rabbitmq"
MIRROR_POLICY = ('{"ha-mode": "exactly", "ha-params": 3, '
                 '"ha-sync-mode": "automatic"}')


class RabbitMQDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Cookie-shared cluster join + mirroring policy
    (rabbitmq.clj:24-101)."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing rabbitmq", node)
        os_setup.install(["rabbitmq-server"])
        # one cookie for the whole cluster (rabbitmq.clj:42-50)
        control.exec_(control.lit(
            "service rabbitmq-server stop >/dev/null 2>&1 || true"))
        control.exec_("sh", "-c",
                      f"echo {COOKIE} > /var/lib/rabbitmq/.erlang.cookie")
        control.exec_("chown", "rabbitmq:rabbitmq",
                      "/var/lib/rabbitmq/.erlang.cookie")
        control.exec_("chmod", "600", "/var/lib/rabbitmq/.erlang.cookie")
        control.exec_("service", "rabbitmq-server", "start")
        primary = (test.get("nodes") or [node])[0]
        if node != primary:
            control.exec_("rabbitmqctl", "stop_app")
        core.synchronize(test, timeout_s=600.0)
        if node != primary:
            control.exec_("rabbitmqctl", "join_cluster", f"rabbit@{primary}")
            control.exec_("rabbitmqctl", "start_app")
        core.synchronize(test, timeout_s=600.0)
        # mirror jepsen.* queues across 3 nodes (rabbitmq.clj:82-88)
        control.exec_("rabbitmqctl", "set_policy", "ha-maj", "jepsen.",
                      MIRROR_POLICY)
        cu.await_tcp_port(PORT, host=node, timeout_s=120.0)

    def teardown(self, test, node):
        # the reference nukes the beam VM and mnesia (rabbitmq.clj:91-101)
        cu.grepkill("beam.smp")
        cu.grepkill("epmd")
        cu.rm_rf("/var/lib/rabbitmq/mnesia/")
        control.exec_(control.lit(
            "service rabbitmq-server stop >/dev/null 2>&1 || true"))

    def start(self, test, node):
        control.exec_("service", "rabbitmq-server", "start")

    def kill(self, test, node):
        cu.grepkill("beam.smp")

    def pause(self, test, node):
        cu.grepkill("beam.smp", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("beam.smp", sig="CONT")

    def log_files(self, test, node):
        return ["/var/log/rabbitmq/rabbit.log"]


class RabbitMQClient(Client):
    """Queue ops over AMQP with publisher confirms."""

    def __init__(self, timeout_s: float = 10.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node
        self.conn: AmqpConnection | None = None

    def open(self, test, node):
        c = RabbitMQClient(self.timeout_s, node)
        c.conn = AmqpConnection(node, PORT, timeout_s=self.timeout_s)
        # confirm mode is per-channel and sticky — select once here
        # (also covers interpreter reopens, which skip setup())
        c.conn.confirm_select()
        return c

    def setup(self, test):
        self.conn.queue_declare(QUEUE, durable=True)

    def _dequeue_one(self):
        got = self.conn.get(QUEUE)
        if got is None:
            return None
        tag, body = got
        value = int(body.decode())
        # even if the ack is lost the message is redelivered — dequeue
        # delivery already happened (the reference's auto-ack rationale,
        # rabbitmq.clj:105-110)
        try:
            self.conn.ack(tag)
        except (AmqpError, OSError):
            pass
        return value

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "enqueue":
                confirmed = self.conn.publish(QUEUE, str(v).encode(),
                                              mandatory=True,
                                              persistent=True)
                return {**op, "type": "ok" if confirmed else "fail"}
            if f == "dequeue":
                value = self._dequeue_one()
                if value is None:
                    return {**op, "type": "fail", "error": ["empty"]}
                return {**op, "type": "ok", "value": value}
            if f == "drain":
                drained: list = []
                try:
                    while True:
                        value = self._dequeue_one()
                        if value is None:
                            return {**op, "type": "ok", "value": drained}
                        drained.append(value)
                except (AmqpError, TimeoutError, ConnectionError,
                        OSError) as e:
                    # partial drains carry what was definitely consumed
                    return {**op, "type": "info", "value": drained,
                            "error": ["net", str(e)]}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except AmqpError as e:
            kind = "fail" if f == "dequeue" else "info"
            return {**op, "type": kind, "error": ["amqp", e.code, e.text]}
        except (TimeoutError, ConnectionError, OSError) as e:
            # an enqueue without a confirm is indeterminate; a dequeue
            # that died pre-delivery is redelivered later → fail is safe
            kind = "fail" if f == "dequeue" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


SEM_QUEUE = "jepsen.semaphore"


class SemaphoreClient(Client):
    """The one-message-queue mutex (rabbitmq.clj:178-255): acquire =
    basic.get without ack (we hold the unacked delivery), release =
    basic.reject with requeue. A release whose channel already died is
    still an ``ok`` — the broker requeues unacked messages itself."""

    def __init__(self, timeout_s: float = 10.0, node: str | None = None,
                 shared: dict | None = None):
        import threading
        self.timeout_s = timeout_s
        self.node = node
        self.shared = shared if shared is not None else {
            "seeded": False, "lock": threading.Lock()}
        self.conn: AmqpConnection | None = None
        self.tag: int | None = None

    def open(self, test, node):
        c = SemaphoreClient(self.timeout_s, node, self.shared)
        c.conn = AmqpConnection(node, PORT, timeout_s=self.timeout_s)
        return c

    def setup(self, test):
        self.conn.queue_declare(SEM_QUEUE, durable=True)
        # exactly ONE token message, seeded once across all clients
        # (rabbitmq.clj:232-243's compare-and-set); client setups run in
        # parallel threads, so the check-then-seed must hold a lock —
        # double-seeding would put two tokens in the queue and fabricate
        # mutual-exclusion violations
        with self.shared["lock"]:
            if self.shared.get("seeded"):
                return
            self.conn.confirm_select()
            self.conn.queue_purge(SEM_QUEUE)
            if not self.conn.publish(SEM_QUEUE, b"", mandatory=False):
                raise RuntimeError("couldn't enqueue semaphore token")
            self.shared["seeded"] = True

    def invoke(self, test, op):
        f = op.get("f")
        try:
            if f == "acquire":
                if self.tag is not None:
                    return {**op, "type": "fail",
                            "error": ["already-held"]}
                got = self.conn.get(SEM_QUEUE, no_ack=False)
                if got is None:
                    return {**op, "type": "fail"}  # lock busy
                self.tag, _body = got
                return {**op, "type": "ok"}
            if f == "release":
                if self.tag is None:
                    return {**op, "type": "fail", "error": ["not-held"]}
                tag, self.tag = self.tag, None
                try:
                    self.conn.reject(tag, requeue=True)
                except (AmqpError, TimeoutError, ConnectionError, OSError):
                    pass  # dead channel requeues the token server-side
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except AmqpError as e:
            kind = "fail" if f == "acquire" else "info"
            return {**op, "type": kind, "error": ["amqp", e.code, e.text]}
        except (TimeoutError, ConnectionError, OSError) as e:
            # an indeterminate acquire may still hold the delivery on the
            # broker until the channel dies, when it requeues
            return {**op, "type": "info", "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


SUPPORTED_WORKLOADS = ("queue", "mutex")


def rabbitmq_test(opts_dict: dict | None = None) -> dict:
    o = dict(opts_dict or {})
    workload = o.get("workload") or SUPPORTED_WORKLOADS[0]
    client = SemaphoreClient() if workload == "mutex" else RabbitMQClient()
    return build_suite_test(
        o, db_name="rabbitmq",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": RabbitMQDB(),
                             "client": client, "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(rabbitmq_test),
    standard_opt_fn(SUPPORTED_WORKLOADS),
    name="jepsen-rabbitmq")


if __name__ == "__main__":
    import sys
    sys.exit(main())
