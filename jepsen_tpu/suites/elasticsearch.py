"""Elasticsearch test suite (reference: elasticsearch/ in
jaydenwen123/jepsen — elasticsearch/src/jepsen/elasticsearch/sets.clj
indexes docs and checks the final search against attempted adds;
dirty_read.clj hunts reads of uncommitted/lost writes).

The client rides the REST API with stdlib urllib. Set adds index one
doc per element followed by the reference's explicit ``_refresh``
before final reads; register CAS uses optimistic concurrency control
(``if_seq_no``/``if_primary_term`` conditional updates), the REST-era
equivalent of the versioned updates the reference's dirty-read client
does through the Java transport.

DB automation installs the archive, sets ``discovery`` to the node
list, and runs the bundled launcher — the ``install!``/``configure!``/
``start!`` cycle of elasticsearch/src/jepsen/elasticsearch/core.clj.
"""
from __future__ import annotations

import logging
import urllib.error

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_json, quote

logger = logging.getLogger("jepsen.elasticsearch")

DEFAULT_VERSION = "7.17.21"
DIR = "/opt/elasticsearch"
LOG_FILE = f"{DIR}/logs/jepsen.log"
PIDFILE = f"{DIR}/es.pid"
PORT = 9200
INDEX = "jepsen"


def archive_url(version: str) -> str:
    return ("https://artifacts.elastic.co/downloads/elasticsearch/"
            f"elasticsearch-{version}-linux-x86_64.tar.gz")


class ElasticsearchDB(db_mod.DB, db_mod.Process, db_mod.Pause,
                      db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing elasticsearch %s", node, self.version)
        cu.install_archive(archive_url(self.version), DIR)
        nodes = test.get("nodes") or []
        conf = "\n".join([
            "cluster.name: jepsen",
            f"node.name: {node}",
            "network.host: 0.0.0.0",
            f"discovery.seed_hosts: [{', '.join(nodes)}]",
            f"cluster.initial_master_nodes: [{', '.join(nodes)}]",
            "xpack.security.enabled: false",
        ]) + "\n"
        from jepsen_tpu import control
        control.exec_("tee", f"{DIR}/config/elasticsearch.yml", stdin=conf)
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/data")

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/bin/elasticsearch")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/bin/elasticsearch", PIDFILE)
        cu.grepkill("org.elasticsearch.bootstrap.Elasticsearch")

    def pause(self, test, node):
        cu.grepkill("org.elasticsearch.bootstrap.Elasticsearch", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("org.elasticsearch.bootstrap.Elasticsearch", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class ElasticsearchClient(Client):
    """Register r/w/cas via seq_no-conditional updates; set via one doc
    per element plus refresh-then-search final reads."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return ElasticsearchClient(self.timeout_s, node)

    def _url(self, path: str) -> str:
        return f"http://{self.node}:{PORT}/{path}"

    def _get_doc(self, k):
        """(value, seq_no, primary_term) or (None, None, None)."""
        try:
            doc = http_json(self._url(f"{INDEX}/_doc/{quote(k)}"),
                            timeout_s=self.timeout_s)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None, None, None
            raise
        return (doc["_source"]["v"], doc["_seq_no"], doc["_primary_term"])

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if test.get("dirty-read"):
                out = self._dirty_read_op(op, f, v)
                if out is not None:
                    return out
            if f == "add":
                http_json(self._url(f"{INDEX}-set/_doc/{quote(v)}"
                                    "?wait_for_active_shards=all"),
                          {"v": v}, method="PUT", timeout_s=self.timeout_s)
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                # final read: explicit refresh first (sets.clj pattern),
                # then page the full set — a size-capped single search
                # silently truncates >10k elements into false "lost"
                http_json(self._url(f"{INDEX}-set/_refresh"), method="POST",
                          timeout_s=self.timeout_s)
                return {**op, "type": "ok",
                        "value": self._paged_search(f"{INDEX}-set")}
            if f == "read":
                k, _ = v
                value, _s, _t = self._get_doc(k)
                return {**op, "type": "ok", "value": [k, value]}
            if f == "write":
                k, val = v
                http_json(self._url(f"{INDEX}/_doc/{quote(k)}"), {"v": val},
                          method="PUT", timeout_s=self.timeout_s)
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                current, seq_no, term = self._get_doc(k)
                if current != old or seq_no is None:
                    return {**op, "type": "fail"}
                try:
                    http_json(
                        self._url(f"{INDEX}/_doc/{quote(k)}"
                                  f"?if_seq_no={seq_no}"
                                  f"&if_primary_term={term}"),
                        {"v": new}, method="PUT", timeout_s=self.timeout_s)
                except urllib.error.HTTPError as e:
                    if e.code == 409:  # version conflict: lost the race
                        return {**op, "type": "fail"}
                    raise
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def _dirty_read_op(self, op, f, v):
        """The dirty-read probe's op surface (dirty_read.clj:52-104):
        unique-doc writes, point reads (absent => fail, not an anomaly),
        an explicit refresh, and paged strong reads."""
        if f == "write":
            http_json(self._url(f"{INDEX}-dr/_doc/{int(v)}"),
                      {"v": int(v)}, method="PUT",
                      timeout_s=self.timeout_s)
            return {**op, "type": "ok"}
        if f == "read" and v is not None:
            try:
                doc = http_json(self._url(f"{INDEX}-dr/_doc/{int(v)}"),
                                timeout_s=self.timeout_s)
            except urllib.error.HTTPError as e:
                if e.code == 404:
                    return {**op, "type": "fail", "error": ["not-found"]}
                raise
            if not doc.get("found"):
                return {**op, "type": "fail", "error": ["not-found"]}
            return {**op, "type": "ok"}
        if f == "refresh":
            http_json(self._url(f"{INDEX}-dr/_refresh"), method="POST",
                      timeout_s=self.timeout_s)
            return {**op, "type": "ok"}
        if f == "strong-read":
            return {**op, "type": "ok",
                    "value": self._paged_search(f"{INDEX}-dr")}
        return None

    def _paged_search(self, index: str) -> list:
        """The whole index via sorted search_after pages (one shared
        pagination for the set final read and the dirty-read probe's
        strong reads)."""
        elems: list = []
        after = None
        while True:
            body = {"size": 10000, "query": {"match_all": {}},
                    "sort": [{"v": "asc"}]}
            if after is not None:
                body["search_after"] = after
            res = http_json(self._url(f"{index}/_search"),
                            body, timeout_s=self.timeout_s)
            hits = res["hits"]["hits"]
            elems.extend(h["_source"]["v"] for h in hits)
            if len(hits) < 10000:
                return elems
            after = hits[-1]["sort"]

    def close(self, test):
        pass


SUPPORTED_WORKLOADS = ("set", "register", "dirty-read")


def elasticsearch_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="elasticsearch",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": ElasticsearchDB(o.get("version", DEFAULT_VERSION)),
            "client": ElasticsearchClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(elasticsearch_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-elasticsearch")


if __name__ == "__main__":
    import sys
    sys.exit(main())
