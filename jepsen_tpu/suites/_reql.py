"""Minimal ReQL wire driver for the rethinkdb suite (reference:
rethinkdb/src/jepsen/rethinkdb/ rides the clj-rethinkdb JVM driver;
this is the from-scratch equivalent).

Protocol (V0_4 + JSON): the client sends a 4-byte little-endian magic
``0x400c2d20``, a length-prefixed auth key, and the JSON-protocol magic
``0x7e6970c7``; the server answers with a NUL-terminated ``SUCCESS``.
Queries are ``token(8B LE) + length(4B LE) + JSON`` where the JSON is
``[START, term, optargs]``; responses echo the token and carry
``{"t": response-type, "r": [...]}``.

Terms are JSON arrays ``[term-code, args, optargs?]``; the builders
below cover the document-CAS workload: db/table/get/insert/update plus
the func/branch/eq/error combinators the CAS lambda needs
(document_cas.clj:95-105).
"""
from __future__ import annotations

import json
import socket
import struct

V0_4 = 0x400c2d20
PROTOCOL_JSON = 0x7e6970c7

START = 1

SUCCESS_ATOM = 1
SUCCESS_SEQUENCE = 2
SUCCESS_PARTIAL = 3
CLIENT_ERROR = 16
COMPILE_ERROR = 17
RUNTIME_ERROR = 18

# term codes (ql2.proto)
MAKE_ARRAY = 2
VAR = 10
ERROR = 12
DB = 14
TABLE = 15
GET = 16
EQ = 17
ADD = 24
GET_FIELD = 31
MAP = 38
COERCE_TO = 51
UPDATE = 53
INSERT = 56
DB_CREATE = 57
TABLE_CREATE = 60
BRANCH = 65
FUNC = 69
DEFAULT = 92
RECONFIGURE = 176


class ReqlError(Exception):
    """A ReQL client/compile/runtime error response."""

    def __init__(self, rtype: int, messages):
        super().__init__(f"{rtype}: {messages}")
        self.rtype = rtype
        self.messages = messages


# -- term builders ----------------------------------------------------------

def db(name: str):
    return [DB, [name]]


def table(db_term, name: str, read_mode: str | None = None):
    opt = {"read_mode": read_mode} if read_mode else {}
    return [TABLE, [db_term, name], opt] if opt else [TABLE, [db_term, name]]


def get(table_term, key):
    return [GET, [table_term, key]]


def get_field(row, field: str):
    return [GET_FIELD, [row, field]]


def eq(a, b):
    return [EQ, [a, b]]


def branch(cond, then, else_):
    return [BRANCH, [cond, then, else_]]


def error(msg: str):
    return [ERROR, [msg]]


def func(body):
    """A one-argument ReQL lambda; the argument is var 1."""
    return [FUNC, [[MAKE_ARRAY, [1]], body]]


def var(n: int):
    return [VAR, [n]]


def default(term, dflt):
    return [DEFAULT, [term, dflt]]


def insert(table_term, doc: dict, conflict: str = "update"):
    return [INSERT, [table_term, {k: v for k, v in doc.items()}],
            {"conflict": conflict}]


def update(selection, func_term):
    return [UPDATE, [selection, func_term]]


def db_create(name: str):
    return [DB_CREATE, [name]]


def table_create(db_term, name: str, replicas: int | None = None):
    opt = {"replicas": replicas} if replicas else {}
    return ([TABLE_CREATE, [db_term, name], opt] if opt
            else [TABLE_CREATE, [db_term, name]])


def add(a, b):
    return [ADD, [a, b]]


def map_(seq, func_term):
    return [MAP, [seq, func_term]]


def coerce_to(term, type_name: str):
    return [COERCE_TO, [term, type_name]]


def reconfigure(table_term, replicas: dict, primary_tag: str,
                shards: int = 1):
    """table.reconfigure({shards, replicas: {tag: n}, primary_replica_tag})
    — the topology-change admin term (rethinkdb.clj:180-193)."""
    return [RECONFIGURE, [table_term],
            {"shards": shards, "replicas": dict(replicas),
             "primary_replica_tag": primary_tag}]


class ReqlConnection:
    """One V0_4/JSON connection; ``run`` sends a START query and returns
    the decoded result."""

    def __init__(self, host: str, port: int = 28015, auth_key: str = "",
                 timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self._token = 0
        try:
            self._handshake(auth_key)
        except BaseException:
            self.sock.close()
            raise

    def _recv_exact(self, n: int) -> bytes:
        from jepsen_tpu.suites._wire import recv_exact
        return recv_exact(self.sock, n)

    def _handshake(self, auth_key: str) -> None:
        key = auth_key.encode()
        self.sock.sendall(struct.pack("<I", V0_4)
                          + struct.pack("<I", len(key)) + key
                          + struct.pack("<I", PROTOCOL_JSON))
        buf = b""
        while not buf.endswith(b"\x00"):
            chunk = self.sock.recv(64)
            if not chunk:
                raise ConnectionError("connection closed during handshake")
            buf += chunk
        msg = buf[:-1].decode()
        if msg != "SUCCESS":
            raise ConnectionError(f"handshake rejected: {msg}")

    def run(self, term):
        """Runs one START query; returns the atom (or sequence list)."""
        self._token += 1
        token = self._token
        payload = json.dumps([START, term, {}]).encode()
        self.sock.sendall(struct.pack("<Q", token)
                          + struct.pack("<I", len(payload)) + payload)
        rtoken, size = struct.unpack("<QI", self._recv_exact(12))
        if rtoken != token:
            raise ConnectionError(
                f"response token {rtoken} != query token {token}")
        resp = json.loads(self._recv_exact(size).decode())
        rtype = resp.get("t")
        if rtype in (CLIENT_ERROR, COMPILE_ERROR, RUNTIME_ERROR):
            raise ReqlError(rtype, resp.get("r"))
        r = resp.get("r", [])
        if rtype == SUCCESS_ATOM:
            return r[0] if r else None
        return r  # sequence (partials unsupported: workloads read atoms)

    def close(self) -> None:
        from jepsen_tpu.suites._wire import close_quietly
        close_quietly(self.sock)
