"""Apache Ignite test suite (reference: ignite/ in jaydenwen123/jepsen
— ignite/src/jepsen/ignite/register.clj checks a linearizable cache
register through Ignite's atomic cache ops; bank.clj runs transfer
transactions in TRANSACTIONAL cache txns with a configurable
concurrency/isolation matrix).

Two transports:

- **register** rides Ignite's REST API (the ignite-rest-http module):
  ``?cmd=get/put/cas`` against an atomic REPLICATED cache, where
  ``cas`` is Ignite's native compare-and-put (``val2`` = expected) —
  so the register workload's CAS is a single server-side atomic op, no
  read-modify-write window.
- **bank** rides the from-scratch thin-client binary protocol
  (:mod:`jepsen_tpu.suites._ignite`): OP_TX_START/OP_TX_END client
  transactions around cache get/put on a TRANSACTIONAL cache — the
  wire equivalent of the reference's ``.txStart`` + get/put/commit
  dance (bank.clj:88-110), with ``--transaction-concurrency`` and
  ``--transaction-isolation`` mirroring the reference's matrix
  (runner.clj option surface).

DB automation unpacks the binary release, enables the REST module,
writes static TcpDiscovery IP-finder config over the node list
(declaring both caches, so no client-side cache-configuration codec is
needed), and runs ignite.sh.
"""
from __future__ import annotations

import logging
import socket
import time
import urllib.error
import urllib.parse

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_json
from jepsen_tpu.suites._ignite import IgniteError as WireError, ThinClient

logger = logging.getLogger("jepsen.ignite")

DEFAULT_VERSION = "2.16.0"
DIR = "/opt/ignite"
LOG_FILE = f"{DIR}/jepsen.log"
PIDFILE = f"{DIR}/ignite.pid"
REST_PORT = 8080
THIN_PORT = 10800
CACHE = "jepsen"
BANK_CACHE = "ACCOUNTS"

CONFIG_XML = """<?xml version="1.0" encoding="UTF-8"?>
<beans xmlns="http://www.springframework.org/schema/beans"
       xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance"
       xsi:schemaLocation="http://www.springframework.org/schema/beans
       http://www.springframework.org/schema/beans/spring-beans.xsd">
  <bean id="ignite.cfg"
        class="org.apache.ignite.configuration.IgniteConfiguration">
    <property name="cacheConfiguration">
      <list>
        <bean class="org.apache.ignite.configuration.CacheConfiguration">
          <property name="name" value="%(cache)s"/>
          <property name="cacheMode" value="REPLICATED"/>
          <property name="atomicityMode" value="ATOMIC"/>
          <property name="writeSynchronizationMode" value="FULL_SYNC"/>
        </bean>
        <bean class="org.apache.ignite.configuration.CacheConfiguration">
          <property name="name" value="%(bank_cache)s"/>
          <property name="cacheMode" value="REPLICATED"/>
          <property name="atomicityMode" value="TRANSACTIONAL"/>
          <property name="writeSynchronizationMode" value="FULL_SYNC"/>
        </bean>
      </list>
    </property>
    <property name="discoverySpi">
      <bean class="org.apache.ignite.spi.discovery.tcp.TcpDiscoverySpi">
        <property name="ipFinder">
          <bean class="org.apache.ignite.spi.discovery.tcp.ipfinder.vm.TcpDiscoveryVmIpFinder">
            <property name="addresses">
              <list>%(addresses)s</list>
            </property>
          </bean>
        </property>
      </bean>
    </property>
  </bean>
</beans>
"""


def archive_url(version: str) -> str:
    return ("https://archive.apache.org/dist/ignite/"
            f"{version}/apache-ignite-{version}-bin.zip")


class IgniteDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing ignite %s", node, self.version)
        from jepsen_tpu import control
        cu.install_archive(archive_url(self.version), DIR)
        # REST API ships disabled: enable the optional module
        control.exec_(control.lit(
            f"cp -rn {DIR}/libs/optional/ignite-rest-http "
            f"{DIR}/libs/ 2>/dev/null || true"))
        addresses = "".join(f"<value>{n}:47500..47509</value>"
                            for n in (test.get("nodes") or []))
        control.exec_("tee", f"{DIR}/config/jepsen.xml",
                      stdin=CONFIG_XML % {"cache": CACHE,
                                          "bank_cache": BANK_CACHE,
                                          "addresses": addresses})
        self.start(test, node)
        cu.await_tcp_port(REST_PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/work")

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/bin/ignite.sh", f"{DIR}/config/jepsen.xml")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/bin/ignite.sh", PIDFILE)
        cu.grepkill("org.apache.ignite.startup.cmdline.CommandLineStartup")

    def pause(self, test, node):
        cu.grepkill("org.apache.ignite.startup.cmdline.CommandLineStartup",
                    sig="STOP")

    def resume(self, test, node):
        cu.grepkill("org.apache.ignite.startup.cmdline.CommandLineStartup",
                    sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class IgniteClient(Client):
    """Register ops via REST ``cmd=get/put/cas`` on the replicated cache."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return IgniteClient(self.timeout_s, node)

    def _cmd(self, **params):
        qs = urllib.parse.urlencode({"cacheName": CACHE, **params})
        doc = http_json(f"http://{self.node}:{REST_PORT}/ignite?{qs}",
                        timeout_s=self.timeout_s)
        if doc.get("successStatus") != 0:
            raise IgniteError(doc.get("error") or str(doc))
        return doc.get("response")

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "read":
                k, _ = v
                raw = self._cmd(cmd="get", key=f"r{k}")
                return {**op, "type": "ok",
                        "value": [k, int(raw) if raw is not None else None]}
            if f == "write":
                k, val = v
                self._cmd(cmd="put", key=f"r{k}", val=str(val))
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                ok = self._cmd(cmd="cas", key=f"r{k}", val=str(new),
                               val2=str(old))
                return {**op, "type": "ok" if ok else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except IgniteError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["ignite", str(e)]}
        except urllib.error.HTTPError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


class IgniteError(Exception):
    pass


class IgniteBankClient(Client):
    """Bank transfers in thin-client transactions (the wire counterpart
    of bank.clj's BankClient :66-110): read = txStart + getAll + commit;
    transfer = txStart + two gets + two puts + commit, failing cleanly
    (with a committed empty txn, like the reference) when the source
    balance would go negative."""

    def __init__(self, concurrency: str = "pessimistic",
                 isolation: str = "repeatable-read",
                 node: str | None = None, conn: ThinClient | None = None,
                 timeout_s: float = 10.0):
        self.concurrency = concurrency
        self.isolation = isolation
        self.node = node
        self.conn = conn
        self.timeout_s = timeout_s

    def open(self, test, node):
        conn = ThinClient(node, THIN_PORT, timeout_s=self.timeout_s)
        conn.connect()
        return IgniteBankClient(self.concurrency, self.isolation, node,
                                conn, self.timeout_s)

    def setup(self, test):
        # every node's client seeds concurrently (core runs setup once
        # per node): balances only written when absent, under one
        # transaction, with commit conflicts treated as "another seeder
        # won" and retried until the accounts verifiably exist
        accounts = list(test.get("accounts", range(8)))
        per = test.get("total-amount", 80) // max(len(accounts), 1)
        for _ in range(20):
            try:
                self.conn.tx_start(self.concurrency, self.isolation)
                existing = self.conn.cache_get_all(BANK_CACHE, accounts)
                missing = [a for a in accounts if existing.get(a) is None]
                for a in missing:
                    self.conn.cache_put(BANK_CACHE, a, per)
                self.conn.tx_end(True)
                if not missing:
                    return
            except WireError:
                self._abort_quietly()
                time.sleep(0.2)
            except (ConnectionError, socket.timeout, OSError):
                self.conn.tx_id = None
                raise
        raise WireError(-1, "bank accounts never fully seeded")

    def _abort_quietly(self):
        try:
            self.conn.tx_end(False)
        except (WireError, ConnectionError, socket.timeout, OSError):
            self.conn.tx_id = None

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        accounts = list(test.get("accounts", range(8)))
        committing = False
        try:
            if self.conn.sock is None:   # dropped after a net error
                self.conn.connect()
            if f == "read":
                self.conn.tx_start(self.concurrency, self.isolation)
                balances = self.conn.cache_get_all(BANK_CACHE, accounts)
                committing = True
                self.conn.tx_end(True)
                return {**op, "type": "ok",
                        "value": {a: balances.get(a) for a in accounts}}
            if f == "transfer":
                frm, to = v["from"], v["to"]
                amount = v["amount"]
                self.conn.tx_start(self.concurrency, self.isolation)
                b1 = (self.conn.cache_get(BANK_CACHE, frm) or 0) - amount
                b2 = (self.conn.cache_get(BANK_CACHE, to) or 0) + amount
                if b1 < 0:
                    self.conn.tx_end(True)   # nothing written: commit ok
                    return {**op, "type": "fail",
                            "error": ["negative", frm, b1]}
                self.conn.cache_put(BANK_CACHE, frm, b1)
                self.conn.cache_put(BANK_CACHE, to, b2)
                committing = True
                self.conn.tx_end(True)
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except WireError as e:
            # a server-side error before commit (lock conflict, txn
            # timeout) rolls the txn back: a clean fail. An error FROM
            # the commit itself is indeterminate for transfers -> info.
            self._abort_quietly()
            kind = "info" if committing and f == "transfer" else "fail"
            return {**op, "type": kind, "error": ["ignite", e.message]}
        except (ConnectionError, socket.timeout, OSError) as e:
            # half-read stream: drop the connection, reconnect next op
            self.conn.close()
            kind = "fail" if f == "read" or not committing else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self._abort_quietly()
            self.conn.close()


SUPPORTED_WORKLOADS = ("register", "bank")


def ignite_test(opts_dict: dict | None = None) -> dict:
    o = dict(opts_dict or {})

    def make_real(opts):
        if (o.get("workload") or SUPPORTED_WORKLOADS[0]) == "bank":
            client = IgniteBankClient(
                opts.get("transaction_concurrency", "pessimistic"),
                opts.get("transaction_isolation", "repeatable-read"))
        else:
            client = IgniteClient()
        return {"db": IgniteDB(opts.get("version", DEFAULT_VERSION)),
                "client": client, "os": Debian()}

    return build_suite_test(
        o, db_name="ignite", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=make_real)


def _ignite_opts(p):
    p.add_argument("--version", default=DEFAULT_VERSION)
    p.add_argument("--transaction-concurrency", default="pessimistic",
                   choices=["optimistic", "pessimistic"],
                   dest="transaction_concurrency")
    p.add_argument("--transaction-isolation", default="repeatable-read",
                   choices=["read-committed", "repeatable-read",
                            "serializable"],
                   dest="transaction_isolation")


main = cli.single_test_cmd(
    standard_test_fn(ignite_test,
                     extra_keys=("version", "transaction_concurrency",
                                 "transaction_isolation")),
    standard_opt_fn(SUPPORTED_WORKLOADS, extra=_ignite_opts),
    name="jepsen-ignite")


if __name__ == "__main__":
    import sys
    sys.exit(main())
