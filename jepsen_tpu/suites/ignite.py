"""Apache Ignite test suite (reference: ignite/ in jaydenwen123/jepsen
— ignite/src/jepsen/ignite/register.clj checks a linearizable cache
register through Ignite's atomic cache ops; bank.clj runs transfer
transactions over the Java client).

The client rides Ignite's REST API (the ignite-rest-http module):
``?cmd=get/put/cas`` against an atomic REPLICATED cache, where ``cas``
is Ignite's native compare-and-put (``val2`` = expected) — so the
register workload's CAS is a single server-side atomic op, no
read-modify-write window. The bank workload needs the Java client's
transactions and stays out of REST scope (run it against the SQL
suites instead). DB automation unpacks the binary release, enables the
REST module, writes static TcpDiscovery IP-finder config over the node
list, and runs ignite.sh.
"""
from __future__ import annotations

import logging
import urllib.error
import urllib.parse

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_json

logger = logging.getLogger("jepsen.ignite")

DEFAULT_VERSION = "2.16.0"
DIR = "/opt/ignite"
LOG_FILE = f"{DIR}/jepsen.log"
PIDFILE = f"{DIR}/ignite.pid"
REST_PORT = 8080
CACHE = "jepsen"

CONFIG_XML = """<?xml version="1.0" encoding="UTF-8"?>
<beans xmlns="http://www.springframework.org/schema/beans"
       xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance"
       xsi:schemaLocation="http://www.springframework.org/schema/beans
       http://www.springframework.org/schema/beans/spring-beans.xsd">
  <bean id="ignite.cfg"
        class="org.apache.ignite.configuration.IgniteConfiguration">
    <property name="cacheConfiguration">
      <bean class="org.apache.ignite.configuration.CacheConfiguration">
        <property name="name" value="%(cache)s"/>
        <property name="cacheMode" value="REPLICATED"/>
        <property name="atomicityMode" value="ATOMIC"/>
        <property name="writeSynchronizationMode" value="FULL_SYNC"/>
      </bean>
    </property>
    <property name="discoverySpi">
      <bean class="org.apache.ignite.spi.discovery.tcp.TcpDiscoverySpi">
        <property name="ipFinder">
          <bean class="org.apache.ignite.spi.discovery.tcp.ipfinder.vm.TcpDiscoveryVmIpFinder">
            <property name="addresses">
              <list>%(addresses)s</list>
            </property>
          </bean>
        </property>
      </bean>
    </property>
  </bean>
</beans>
"""


def archive_url(version: str) -> str:
    return ("https://archive.apache.org/dist/ignite/"
            f"{version}/apache-ignite-{version}-bin.zip")


class IgniteDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing ignite %s", node, self.version)
        from jepsen_tpu import control
        cu.install_archive(archive_url(self.version), DIR)
        # REST API ships disabled: enable the optional module
        control.exec_(control.lit(
            f"cp -rn {DIR}/libs/optional/ignite-rest-http "
            f"{DIR}/libs/ 2>/dev/null || true"))
        addresses = "".join(f"<value>{n}:47500..47509</value>"
                            for n in (test.get("nodes") or []))
        control.exec_("tee", f"{DIR}/config/jepsen.xml",
                      stdin=CONFIG_XML % {"cache": CACHE,
                                          "addresses": addresses})
        self.start(test, node)
        cu.await_tcp_port(REST_PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/work")

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/bin/ignite.sh", f"{DIR}/config/jepsen.xml")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/bin/ignite.sh", PIDFILE)
        cu.grepkill("org.apache.ignite.startup.cmdline.CommandLineStartup")

    def pause(self, test, node):
        cu.grepkill("org.apache.ignite.startup.cmdline.CommandLineStartup",
                    sig="STOP")

    def resume(self, test, node):
        cu.grepkill("org.apache.ignite.startup.cmdline.CommandLineStartup",
                    sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class IgniteClient(Client):
    """Register ops via REST ``cmd=get/put/cas`` on the replicated cache."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return IgniteClient(self.timeout_s, node)

    def _cmd(self, **params):
        qs = urllib.parse.urlencode({"cacheName": CACHE, **params})
        doc = http_json(f"http://{self.node}:{REST_PORT}/ignite?{qs}",
                        timeout_s=self.timeout_s)
        if doc.get("successStatus") != 0:
            raise IgniteError(doc.get("error") or str(doc))
        return doc.get("response")

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "read":
                k, _ = v
                raw = self._cmd(cmd="get", key=f"r{k}")
                return {**op, "type": "ok",
                        "value": [k, int(raw) if raw is not None else None]}
            if f == "write":
                k, val = v
                self._cmd(cmd="put", key=f"r{k}", val=str(val))
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                ok = self._cmd(cmd="cas", key=f"r{k}", val=str(new),
                               val2=str(old))
                return {**op, "type": "ok" if ok else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except IgniteError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["ignite", str(e)]}
        except urllib.error.HTTPError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


class IgniteError(Exception):
    pass


SUPPORTED_WORKLOADS = ("register",)


def ignite_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="ignite", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": IgniteDB(o.get("version", DEFAULT_VERSION)),
            "client": IgniteClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(ignite_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-ignite")


if __name__ == "__main__":
    import sys
    sys.exit(main())
