"""Aerospike test suite (reference: aerospike/src/aerospike/ — the
strong-consistency-mode KV store whose CAS register, counter, and set
tests exposed lost updates under partitions).

The client rides the bundled binary wire protocol (``_aerospike.py``):
reads return (value, generation) from a single-record transaction, and
CAS is a generation-conditioned write — read the register's generation,
verify the value, then write with the GENERATION policy bit so the
server rejects the write (GENERATION_ERROR) if anything committed in
between, exactly the optimistic scheme of the reference's cas-register
client (aerospike/cas_register.clj).

DB automation per aerospike/support.clj: install the server package,
write a mesh-heartbeat config listing every node with a
strong-consistency namespace, start, then ``roster-set`` + ``recluster``
via asinfo from the primary.

Faults beyond the generic families: ``--fault killer`` (the capped
kill/restart/revive/recluster vocabulary, aerospike/nemesis.clj) and
``--fault pause-writes`` with ``--workload pause`` (the coordinated
pause-to-lose-writes probe, aerospike/pause.clj — see
workloads/pause_workload.py).
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu import nemesis as nemesis_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)
from jepsen_tpu.suites._aerospike import (AerospikeConnection,
                                          AerospikeError)

logger = logging.getLogger("jepsen.aerospike")

PORT = 3000
HEARTBEAT_PORT = 3002
FABRIC_PORT = 3001
NAMESPACE = "jepsen"
SET_NAME = "registers"
CONF = "/etc/aerospike/aerospike.conf"
LOG_FILE = "/var/log/aerospike/aerospike.log"


def config(test: dict, node: str) -> str:
    """Mesh-heartbeat config with a strong-consistency namespace
    (aerospike/support.clj's aerospike.conf resource)."""
    mesh_seeds = "\n".join(
        f"                mesh-seed-address-port {n} {HEARTBEAT_PORT}"
        for n in (test.get("nodes") or []))
    return f"""
service {{
        proto-fd-max 15000
        node-id-interface eth0
}}
logging {{
        file {LOG_FILE} {{
                context any info
        }}
}}
network {{
        service {{
                address any
                port {PORT}
        }}
        heartbeat {{
                mode mesh
                address any
                port {HEARTBEAT_PORT}
{mesh_seeds}
                interval 150
                timeout 10
        }}
        fabric {{
                port {FABRIC_PORT}
        }}
}}
namespace {NAMESPACE} {{
        replication-factor 3
        strong-consistency true
        storage-engine memory {{
                data-size 1G
        }}
}}
"""


class AerospikeDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Package install, SC-namespace config, roster-set + recluster
    (aerospike/support.clj:213-280)."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing aerospike", node)
        os_setup.install(["aerospike-server-community", "aerospike-tools"])
        cu.write_file(config(test, node), CONF)
        control.exec_("service", "aerospike", "restart")
        cu.await_tcp_port(PORT, host=node, timeout_s=300.0)
        core.synchronize(test, timeout_s=600.0)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            # strong-consistency roster: observe → set → recluster
            # (support.clj:135-211), through our own info protocol
            conn = AerospikeConnection(node, PORT, namespace=NAMESPACE,
                                       timeout_s=30.0)
            try:
                cmd = f"roster:namespace={NAMESPACE}"
                reply = conn.info(cmd).get(cmd, "")
                observed = ""
                for part in reply.split(":"):
                    if part.startswith("observed_nodes="):
                        observed = part.split("=", 1)[1]
                conn.info(f"roster-set:namespace={NAMESPACE};"
                          f"nodes={observed}")
                conn.info("recluster:")
            finally:
                conn.close()
        core.synchronize(test, timeout_s=600.0)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf("/opt/aerospike/data")

    def start(self, test, node):
        control.exec_("service", "aerospike", "start")

    def kill(self, test, node):
        control.exec_(control.lit(
            "service aerospike stop >/dev/null 2>&1 || true"))
        cu.grepkill("asd")

    def pause(self, test, node):
        cu.grepkill("asd", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("asd", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class AerospikeClient(Client):
    """Generation-CAS register client (aerospike/cas_register.clj)."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node
        self.conn: AerospikeConnection | None = None

    def open(self, test, node):
        c = AerospikeClient(self.timeout_s, node)
        c.conn = AerospikeConnection(node, PORT, namespace=NAMESPACE,
                                     set_name=SET_NAME,
                                     timeout_s=self.timeout_s)
        return c

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if test.get("pause-workload"):
                # per-key string-append sets (pause.clj:105-136)
                k, x = v
                if f == "add":
                    self.conn.append(int(k), f" {int(x)}")
                    return {**op, "type": "ok"}
                if f == "read":
                    raw = self.conn.get_string(int(k))
                    return {**op, "type": "ok",
                            "value": [k, sorted(int(e)
                                                for e in raw.split() if e)]}
            if test.get("counter") and f == "add":
                self.conn.incr(0, int(v))
                return {**op, "type": "ok"}
            if test.get("counter") and f == "read" and v is None:
                value, _gen = self.conn.get(0)
                return {**op, "type": "ok", "value": int(value or 0)}
            if f == "add":
                # set adds append ' v' to one record's string bin — the
                # reference's CAS-op set shape (aerospike/set.clj:35)
                self.conn.append(0, f" {int(v)}")
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                raw = self.conn.get_string(0)
                return {**op, "type": "ok",
                        "value": sorted(int(x) for x in raw.split() if x)}
            if f == "read":
                k, _ = v
                value, _gen = self.conn.get(int(k))
                return {**op, "type": "ok", "value": [k, value]}
            if f == "write":
                k, val = v
                self.conn.put(int(k), int(val))
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                value, gen = self.conn.get(int(k))
                if value != old:
                    return {**op, "type": "fail", "error": ["value-mismatch"]}
                applied = self.conn.put(int(k), int(new), generation=gen)
                return {**op, "type": "ok" if applied else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except AerospikeError as e:
            # server-side rejection with a result code: the op did not
            # apply (unavailable partitions in SC mode return codes too)
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["aerospike", e.code]}
        except (TimeoutError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


SUPPORTED_WORKLOADS = ("register", "counter", "set", "pause")


# ---------------------------------------------------------------------------
# Killer nemesis (aerospike/nemesis.clj:17-128): capped kills, restarts,
# and the SC-mode revive/recluster recovery vocabulary
# ---------------------------------------------------------------------------

DEFAULT_MAX_DEAD = 2  # --max-dead-nodes default (aerospike/core.clj:91-94)


class KillerNemesis(nemesis_mod.Nemesis):
    """``kill`` SIGKILLs asd on a random nonempty node subset but never
    lets more than ``max_dead`` nodes stay down at once (capped-conj,
    nemesis.clj:11-15,31-36); ``restart`` brings a subset back;
    ``revive`` + ``recluster`` run the asinfo recovery pair that
    readmits dead-partition data in strong-consistency mode
    (support.clj:142-152)."""

    def __init__(self, max_dead: int = DEFAULT_MAX_DEAD, signal: int = 9,
                 rng=None):
        import random as _random
        import threading
        self.max_dead = max_dead
        self.signal = signal
        self.rng = rng or _random.Random()
        self.dead: set = set()
        # per-node closures run concurrently (_on_nodes/real_pmap); the
        # cap check-then-add must be atomic like the reference's
        # capped-conj swap! (nemesis.clj:11-15) or a slow multi-node
        # kill op blows past max_dead
        self._dead_lock = threading.Lock()

    def fs(self):
        return {"kill", "restart", "revive", "recluster"}

    def invoke(self, test, op):
        from jepsen_tpu.nemesis.db_specific import _on_nodes
        f = op.get("f")
        # subsets come from the generator (nemesis.clj:59-77); a bare op
        # (e.g. the final heal) targets every node
        nodes = op.get("value") or list(test.get("nodes") or [])

        def one(node):
            if f == "kill":
                with self._dead_lock:
                    allowed = (node in self.dead
                               or len(self.dead) < self.max_dead)
                    if allowed:
                        self.dead.add(node)
                if not allowed:
                    return "still-alive"
                control.exec_(control.lit(
                    f"killall -{self.signal} asd "
                    f">/dev/null 2>&1 || true"))
                return "killed"
            if f == "restart":
                control.exec_("service", "aerospike", "restart")
                with self._dead_lock:
                    self.dead.discard(node)
                return "started"
            if f == "revive":
                return control.exec_(control.lit(
                    f"asinfo -v revive:namespace={NAMESPACE} "
                    f"2>&1 || echo not-running"))
            if f == "recluster":
                return control.exec_(control.lit(
                    "asinfo -v recluster: 2>&1 || echo not-running"))
            return "unknown-f"

        return {**op, "type": "info",
                "value": _on_nodes(test, nodes, one)}


def killer_gen():
    """Randomized kill / restart / revive+recluster patterns; kill and
    restart ops carry a random nonempty node subset computed at
    generation time, revive/recluster target every node
    (nemesis.clj:59-94)."""
    from jepsen_tpu import generator as gen

    def subset(test, ctx):
        nodes = list(test.get("nodes") or [])
        return ctx.rng.sample(nodes, ctx.rng.randint(1, len(nodes))) \
            if nodes else []

    def fn(test, ctx):
        pattern = ctx.rng.choice([["kill"], ["restart"],
                                  ["revive", "recluster"]])
        return gen.Seq([
            {"type": "info", "f": f,
             "value": (subset(test, ctx) if f in ("kill", "restart")
                       else list(test.get("nodes") or []))}
            for f in pattern])

    return gen.Fn(fn)


def killer_package(opts: dict) -> dict:
    """--fault killer: the full kill/restart/revive/recluster cycle,
    healed by a final restart + recovery pair."""
    from jepsen_tpu import generator as gen
    interval = opts.get("interval", 10.0)
    return {
        "nemesis": KillerNemesis(
            max_dead=opts.get("max_dead_nodes", DEFAULT_MAX_DEAD)),
        "generator": gen.stagger(interval, killer_gen()),
        "final_generator": gen.Seq([
            {"type": "info", "f": "restart", "value": None},
            {"type": "info", "f": "revive", "value": None},
            {"type": "info", "f": "recluster", "value": None}]),
        "perf": {"name": "killer",
                 "fs": {"kill", "restart", "revive", "recluster"},
                 "start": {"kill"}, "stop": {"restart"}},
    }


# ---------------------------------------------------------------------------
# Pause nemesis (aerospike/pause.clj:40-103): freeze a master so its
# trapped in-flight writes resurface with a far-future local clock
# ---------------------------------------------------------------------------

class PauseNemesis(nemesis_mod.Nemesis):
    """``pause`` / ``resume`` on the op's node list, in one of three
    modes (pause.clj:40-83): ``process`` SIGSTOPs asd; ``net`` injects
    self-removing egress latency (a nohup mini-daemon restores the
    qdisc — raising latency would sever our own SSH session otherwise);
    ``clock`` bumps the node's clock far ahead and snubs it from every
    peer, so its local commits carry unreplicated future timestamps."""

    def __init__(self, mode: str = "process",
                 pause_delay_s: float = 30.0):
        self.mode = mode
        self.pause_delay_s = pause_delay_s

    def fs(self):
        return {"pause", "resume"}

    def _pause(self, test, node):
        from jepsen_tpu.nemesis import time as nt
        if self.mode == "process":
            db = test.get("db")
            if hasattr(db, "pause"):  # one source of asd process control
                db.pause(test, node)
            else:
                cu.grepkill("asd", sig="STOP")
            return "paused"
        if self.mode == "net":
            # qdisc replace tolerates an existing root qdisc; the
            # mini-daemon outlives the wait window (which only starts
            # at the first post-pause ack) with 2x slack
            secs = 2 * int(self.pause_delay_s) + 2
            control.exec_(control.lit(
                f"nohup bash -c 'tc qdisc replace dev eth0 root netem "
                f"delay {int(self.pause_delay_s * 1000)}ms 1ms "
                f"distribution normal; sleep {secs}; "
                f"tc qdisc del dev eth0 root' >/dev/null 2>&1 &"))
            return "net-delayed"
        if self.mode == "clock":
            nt.install()
            nt.bump_time(int(self.pause_delay_s * 1000) * 1000)
            return "clock-bumped"
        return "unknown-mode"

    def _snub(self, test, node):
        """clock mode: partition the bumped node from every peer both
        ways (pause.clj:58-68)."""
        net = test.get("net")
        if net is None:
            return
        for other in test.get("nodes") or []:
            if other != node:
                net.drop(test, node, other)
                net.drop(test, other, node)

    def invoke(self, test, op):
        from jepsen_tpu.nemesis import time as nt
        from jepsen_tpu.nemesis.db_specific import _on_nodes
        f = op.get("f")
        nodes = op.get("value") or list(test.get("nodes") or [])
        if f == "pause":
            if self.mode == "clock":
                # snub FIRST: a bumped clock must never replicate its
                # far-future timestamps (an improvement over the
                # reference's bump-then-isolate order, pause.clj:58-68,
                # whose window is only small because the clock binary
                # pre-installs at setup)
                for node in nodes:
                    self._snub(test, node)
            res = _on_nodes(test, nodes,
                            lambda node: self._pause(test, node))
            return {**op, "type": "info", "value": res}
        if f == "resume":
            if self.mode == "process":
                db = test.get("db")

                def cont(node):
                    if hasattr(db, "resume"):
                        db.resume(test, node)
                    else:
                        cu.grepkill("asd", sig="CONT")
                    return "resumed"

                res = _on_nodes(test, nodes, cont)
            elif self.mode == "net":
                res = "self-healing"  # the qdisc removes itself
            else:  # clock (pause.clj:75-83)
                res = _on_nodes(test, nodes,
                                lambda node: (nt.reset_time(), "reset")[1])
                net = test.get("net")
                if net is not None:
                    net.heal(test)
                others = [n for n in (test.get("nodes") or [])
                          if n not in nodes]
                _on_nodes(test, others, lambda node: control.exec_(
                    control.lit("service aerospike restart "
                                ">/dev/null 2>&1 || true")))
            return {**op, "type": "info", "value": res}
        return {**op, "type": "info", "value": ["unknown-f", f]}


def pause_package(opts: dict, state, mode: str = "process",
                  pause_delay_s: float = 30.0) -> dict:
    """--fault pause-writes: the state-machine-coordinated nemesis half
    of the pause workload (pause.clj:226-233). Registered under its own
    name — "pause" would ALSO trigger the generic db pause package,
    whose uncoordinated ~interval pause/resume cycle owns the same op
    vocabulary in the compose routing and would both shadow this
    nemesis and break the wait window."""
    from jepsen_tpu import generator as gen
    from jepsen_tpu.workloads.pause_workload import PauseNemesisGen
    return {
        "nemesis": PauseNemesis(mode, pause_delay_s),
        "generator": PauseNemesisGen(state),
        "final_generator": gen.Seq([
            {"type": "info", "f": "resume", "value": None}]),
        "perf": {"name": "pause", "fs": {"pause", "resume"},
                 "start": {"pause"}, "stop": {"resume"}},
    }


def aerospike_test(opts_dict: dict | None = None) -> dict:
    from jepsen_tpu.workloads import pause_workload
    o = dict(opts_dict or {})
    max_dead = o.get("max_dead_nodes")
    pause_state = pause_workload.MachineState()
    pause_delay = float(o.get("pause_delay", 30.0))

    def pause_wk(base):
        return {**pause_workload.workload(base, state=pause_state),
                "pause-healthy-delay": float(o.get("healthy_delay", 5.0)),
                "pause-delay": pause_delay}

    if "pause-writes" in (o.get("faults") or ()) \
            and (o.get("workload") or SUPPORTED_WORKLOADS[0]) != "pause":
        # without the pause workload's client generator nothing ever
        # flips paused→wait and the nemesis wedges a node SIGSTOPped
        # for the whole main phase
        raise ValueError("--fault pause-writes requires --workload pause")

    return build_suite_test(
        o, db_name="aerospike",
        supported_workloads=SUPPORTED_WORKLOADS,
        extra_workloads={"pause": pause_wk},
        fault_packages={
            "killer": lambda opts: killer_package(
                {**opts, "max_dead_nodes": max_dead}
                if max_dead is not None else opts),
            "pause-writes": lambda opts: pause_package(
                opts, pause_state, o.get("pause_mode", "process"),
                pause_delay)},
        make_real=lambda o: {"db": AerospikeDB(),
                             "client": AerospikeClient(), "os": Debian()})


main_all = standard_test_all(aerospike_test, SUPPORTED_WORKLOADS,
                             name="jepsen-aerospike")

main = cli.single_test_cmd(
    standard_test_fn(aerospike_test,
                     extra_keys=("max_dead_nodes", "pause_mode",
                                 "pause_delay", "healthy_delay")),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra_faults=("killer", "pause-writes"),
                    extra=lambda p: (
                        p.add_argument(
                            "--max-dead-nodes", dest="max_dead_nodes",
                            type=int, default=None,
                            help="cap on simultaneously-killed nodes "
                                 "(aerospike/core.clj:91-94; default "
                                 f"{DEFAULT_MAX_DEAD})"),
                        p.add_argument("--pause-mode", dest="pause_mode",
                                       default="process",
                                       choices=["process", "net", "clock"]),
                        p.add_argument("--pause-delay", dest="pause_delay",
                                       type=float, default=30.0),
                        p.add_argument("--healthy-delay",
                                       dest="healthy_delay",
                                       type=float, default=5.0))),
    name="jepsen-aerospike")


if __name__ == "__main__":
    import sys
    sys.exit(main())
