"""Chronos test suite (reference: chronos/ in jaydenwen123/jepsen —
chronos/src/jepsen/chronos.clj schedules repeating jobs on a
Mesos+Chronos cluster whose runs append timestamps to per-job files;
chronos/src/jepsen/chronos/checker.clj verifies every *target*
invocation window got a run).

Jobs are added over Chronos's HTTP API (``POST /scheduler/iso8601``
with an ``R<count>/<start>/PT<interval>S`` repeating schedule,
chronos.clj:102-141); each run's command appends an epoch timestamp to
``/tmp/chronos-test/<job>`` on whichever node executes it. The final
read gathers those files from every node via the control layer
(read-runs, chronos.clj:161-170).

The checker re-derives each acknowledged job's target windows —
``start + i*interval`` for ``i < count``, due before
``read-time - epsilon - duration`` — and requires a distinct run in
every ``[target, target + epsilon + forgiveness]`` window
(checker.clj:26-47). Matching runs to targets is earliest-deadline
greedy over sorted windows, which is an exact maximum matching for
interval bigraphs — replacing the reference's loco constraint solver.

DB automation installs the mesosphere packages (mesos master/agent +
chronos, backed by zookeeper) the way mesosphere.clj does.
"""
from __future__ import annotations

import logging
import urllib.error

from jepsen_tpu import checker as chk
from jepsen_tpu import cli, control, db as db_mod, generator as gen
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites._http import NET_ERRORS, http_json

logger = logging.getLogger("jepsen.chronos")

PORT = 4400
RUN_DIR = "/tmp/chronos-test"
EPSILON_FORGIVENESS = 5  # seconds; checker.clj:26-28


# ---------------------------------------------------------------------------
# DB
# ---------------------------------------------------------------------------

class ChronosDB(db_mod.DB, db_mod.LogFiles):
    """mesos master+agent and chronos on every node, zk-coordinated
    (mesosphere.clj:33-84)."""

    def setup(self, test, node):
        from jepsen_tpu import os_setup
        logger.info("%s: installing mesos+chronos", node)
        nodes = test.get("nodes") or []
        zk = ",".join(f"{n}:2181" for n in nodes)
        os_setup.install(["zookeeper", "mesos", "chronos"])
        control.exec_("tee", "/etc/mesos/zk",
                      stdin=f"zk://{zk}/mesos\n")
        quorum = len(nodes) // 2 + 1
        control.exec_("tee", "/etc/mesos-master/quorum",
                      stdin=f"{quorum}\n")
        control.exec_("mkdir", "-p", RUN_DIR)
        for svc in ("zookeeper", "mesos-master", "mesos-slave", "chronos"):
            control.exec_("service", svc, "restart")
        cu.await_tcp_port(PORT, host=node)

    def teardown(self, test, node):
        for svc in ("chronos", "mesos-slave", "mesos-master"):
            try:
                control.exec_("service", svc, "stop")
            except Exception:
                pass
        cu.rm_rf(RUN_DIR)

    def log_files(self, test, node):
        return ["/var/log/chronos/chronos.log", "/var/log/mesos/mesos.log"]


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------

def interval_str(job: dict) -> str:
    """R<count>/<ISO start>/PT<interval>S (chronos.clj:102-107)."""
    import datetime
    start = datetime.datetime.fromtimestamp(
        job["start"], tz=datetime.timezone.utc)
    return (f"R{job['count']}/{start.strftime('%Y-%m-%dT%H:%M:%SZ')}"
            f"/PT{job['interval']}S")


def job_json(job: dict) -> dict:
    return {
        "name": str(job["name"]),
        "command": (f"mkdir -p {RUN_DIR}; "
                    f"date +%s >> {RUN_DIR}/{job['name']}; "
                    f"sleep {job['duration']}"),
        "schedule": interval_str(job),
        "epsilon": f"PT{job['epsilon']}S",
        "owner": "jepsen",
        "async": False,
    }


class ChronosClient(Client):
    def __init__(self, timeout_s: float = 10.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return ChronosClient(self.timeout_s, node)

    def invoke(self, test, op):
        f = op.get("f")
        try:
            if f == "add-job":
                http_json(
                    f"http://{self.node}:{PORT}/scheduler/iso8601",
                    job_json(op["value"]), timeout_s=self.timeout_s)
                return {**op, "type": "ok"}
            if f == "read":
                import time
                runs = read_runs(test)
                return {**op, "type": "ok",
                        "value": {"read-time": time.time(), "runs": runs}}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            return {**op, "type": "info", "error": ["http", e.code]}
        except NET_ERRORS as e:
            return {**op, "type": "info", "error": ["net", str(e)]}

    def close(self, test):
        pass


def read_runs(test) -> dict:
    """{job-name: sorted run epochs} across all nodes (chronos.clj:161-170)."""
    runs: dict = {}

    def gather(node):
        try:
            out = control.exec_star(
                "sh", "-c",
                f"for f in {RUN_DIR}/*; do "
                "[ -f \"$f\" ] && echo \"== $f\" && cat \"$f\"; done")
            return out.out
        except Exception:
            return ""

    for node, text in control.on_nodes(test, gather).items():
        current = None
        for line in (text or "").splitlines():
            if line.startswith("== "):
                current = line[3:].rsplit("/", 1)[-1]
                runs.setdefault(current, [])
            elif current and line.strip().isdigit():
                runs[current].append(int(line.strip()))
    return {name: sorted(ts) for name, ts in runs.items()}


# ---------------------------------------------------------------------------
# Checker (chronos/src/jepsen/chronos/checker.clj)
# ---------------------------------------------------------------------------

def job_targets(read_time: float, job: dict) -> list[tuple[float, float]]:
    """[start, stop] windows that *must* have begun by read time
    (checker.clj job->targets:30-47)."""
    finish = read_time - job["epsilon"] - job["duration"]
    out = []
    for i in range(job["count"]):
        t = job["start"] + i * job["interval"]
        if t >= finish:
            break
        out.append((t, t + job["epsilon"] + EPSILON_FORGIVENESS))
    return out


def match_targets(targets: list[tuple[float, float]],
                  runs: list[float]) -> tuple[list, list]:
    """(matched, unmatched-targets): earliest-deadline-first greedy,
    each run satisfies at most one target."""
    free = sorted(runs)
    matched, unmatched = [], []
    for lo, hi in sorted(targets, key=lambda w: w[1]):
        pick = next((r for r in free if lo <= r <= hi), None)
        if pick is None:
            unmatched.append([lo, hi])
        else:
            free.remove(pick)
            matched.append([lo, hi, pick])
    return matched, unmatched


class ChronosChecker(chk.Checker):
    def check(self, test, history, opts):
        jobs = [op["value"] for op in history
                if op.get("f") == "add-job" and op.get("type") == "ok"]
        read = next((op["value"] for op in reversed(history)
                     if op.get("f") == "read" and op.get("type") == "ok"),
                    None)
        if read is None:
            return {"valid?": "unknown", "error": "no successful final read"}
        job_results = {}
        ok = True
        for job in jobs:
            targets = job_targets(read["read-time"], job)
            runs = read["runs"].get(str(job["name"]), [])
            matched, unmatched = match_targets(targets, runs)
            extra = len(runs) - len(matched)
            job_results[str(job["name"])] = {
                "target-count": len(targets), "run-count": len(runs),
                "matched-count": len(matched), "unmatched": unmatched,
                "extra-run-count": extra,
            }
            if unmatched:
                ok = False
        return {"valid?": ok, "job-count": len(jobs), "jobs": job_results}


# ---------------------------------------------------------------------------
# Workload + test
# ---------------------------------------------------------------------------

def add_jobs():
    """Fresh jobs with randomized duration/epsilon/interval
    (chronos.clj add-job:194-217)."""
    import random
    state = {"n": 0}

    def nxt(test, ctx):
        import time
        state["n"] += 1
        duration = random.randint(0, 9)
        epsilon = 10 + random.randint(0, 19)
        interval = (1 + duration + epsilon + EPSILON_FORGIVENESS
                    + random.randint(0, 59))
        return {"f": "add-job", "value": {
            "name": state["n"],
            "start": time.time() + 2 + random.randint(0, 9),
            "count": 1 + random.randint(0, 99),
            "duration": duration, "epsilon": epsilon,
            "interval": interval}}

    return gen.Fn(nxt)


def chronos_workload(base, **_):
    return {
        "generator": gen.stagger(30.0, add_jobs()),
        "final_generator": gen.once(gen.Fn(
            lambda test, ctx: {"f": "read"})),
        "checker": ChronosChecker(),
    }


SUPPORTED_WORKLOADS = ("jobs",)


def chronos_test(o: dict | None = None) -> dict:
    from jepsen_tpu.suites import build_suite_test
    return build_suite_test(
        o, db_name="chronos", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": ChronosDB(), "client": ChronosClient(),
                             "os": Debian()},
        make_workload=lambda name, base: chronos_workload(base),
        fake_client=FakeChronosClient,
        defaults={"concurrency": 2, "time_limit": 300,
                  "nemesis_interval": 60.0})


class FakeChronosClient(Client):
    """In-memory double: jobs 'run' exactly on schedule — every target
    window gets a punctual run at fake read time."""

    def __init__(self):
        self.jobs: list[dict] = []

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        import time
        f = op.get("f")
        if f == "add-job":
            self.jobs.append(op["value"])
            return {**op, "type": "ok"}
        if f == "read":
            now = time.time()
            runs = {str(j["name"]):
                    [t for t, _ in job_targets(now, j)]
                    for j in self.jobs}
            return {**op, "type": "ok",
                    "value": {"read-time": now, "runs": runs}}
        return {**op, "type": "fail"}

    def close(self, test):
        pass


from jepsen_tpu.suites import standard_opt_fn, standard_test_fn  # noqa: E402

main = cli.single_test_cmd(
    standard_test_fn(chronos_test),
    standard_opt_fn(SUPPORTED_WORKLOADS, nemesis_interval=60.0),
    name="jepsen-chronos")


if __name__ == "__main__":
    import sys
    sys.exit(main())
