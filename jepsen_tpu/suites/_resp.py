"""Minimal RESP (REdis Serialization Protocol) wire client, shared by the
redis-protocol family of suites: redis, raftis (floyd's redis-compatible
raft server, reference raftis/src/jepsen/raftis.clj), and disque (whose
job commands ride the same framing, reference
disque/src/jepsen/disque.clj).

Commands go out as arrays of bulk strings; the five reply types come
back by leading type byte (``+ - : $ *``). No driver dependency — the
point (as with the MySQL/Postgres wire clients in ``_mysql.py`` /
``_postgres.py``) is that suites own their wire protocol end to end, so
fault-injection tests see real socket behavior, not a driver's retry
policy.
"""
from __future__ import annotations

import socket


class RespError(Exception):
    """A server ``-ERR ...`` reply."""


class RespConnection:
    """A minimal RESP client: commands as arrays of bulk strings, replies
    parsed by type byte (+ - : $ *)."""

    def __init__(self, host: str, port: int, timeout_s: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self.buf = self.sock.makefile("rb")

    def command(self, *args):
        out = [f"*{len(args)}\r\n".encode()]
        for a in args:
            data = a if isinstance(a, bytes) else str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(data), data))
        self.sock.sendall(b"".join(out))
        return self._reply()

    def _reply(self):
        line = self.buf.readline()
        if not line:
            raise ConnectionError("connection closed")
        if not line.endswith(b"\r\n"):
            # EOF mid-line (server killed mid-reply): a truncated reply
            # must never surface as a successful value
            raise ConnectionError("truncated reply line")
        kind, rest = line[:1], line[1:].strip()
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n < 0:
                return None
            data = self.buf.read(n + 2)
            if len(data) != n + 2:
                raise ConnectionError("truncated bulk reply")
            return data[:-2].decode()
        if kind == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._reply() for _ in range(n)]
        raise RespError(f"unknown reply type {kind!r}")

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass
