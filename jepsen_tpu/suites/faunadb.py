"""FaunaDB test suite (reference: faunadb/src/jepsen/faunadb/ — a
Calvin-style distributed transactional database; the reference probes
registers, bank transfers, set membership (pages and whole-set reads),
G2/adya phantoms, timestamp monotonicity (monotonic.clj /
multimonotonic.clj), within-transaction internal consistency
(internal.clj), and cluster topology changes (topology.clj +
nemesis.clj's topo-nemesis) through the JVM driver).

Every FaunaDB query is a single transaction POSTed as a JSON-encoded
FQL expression to port 8443 with HTTP Basic auth (the cluster secret as
username) — so each workload op here is one ``http_json`` call carrying
a composed expression tree: register CAS is ``If(Equals(Select(...),
old), Update(...), false)`` evaluated atomically server-side
(faunadb/register.clj's cas shape), bank transfers are a ``Do`` of two
guarded updates, set adds create one instance per element.

DB automation per faunadb/auto.clj: install the ``faunadb`` apt
package, write /etc/faunadb.yml with this node's addresses, start the
service, ``faunadb-admin init`` on the primary and ``join`` elsewhere.
"""
from __future__ import annotations

import base64
import logging
import urllib.error

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.nemesis import membership as _membership
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_error_json, http_json

logger = logging.getLogger("jepsen.faunadb")

PORT = 8443
SECRET = "secret"
YML = "/etc/faunadb.yml"
LOG_FILE = "/var/log/faunadb/core.log"


def config_yml(test: dict, node: str) -> str:
    """/etc/faunadb.yml (faunadb/auto.clj:160-196 shape)."""
    return "\n".join([
        f"auth_root_key: {SECRET}",
        "cluster_name: jepsen",
        f"network_broadcast_address: {node}",
        "network_datacenter_name: replica-1",
        f"network_host_id: {node}",
        "network_listen_address: 0.0.0.0",
        "storage_data_path: /var/lib/faunadb",
        "log_path: /var/log/faunadb",
        "",
    ])


# -- FQL JSON expression builders (the v2 JSON wire forms the JVM driver
# -- emits; each helper returns a plain dict ready to POST) -----------------

def ref_(cls: str, instance_id) -> dict:
    return {"ref": {"@ref": f"classes/{cls}/{instance_id}"}}


def get_(cls: str, instance_id) -> dict:
    return {"get": ref_(cls, instance_id)["ref"]}


def select_data(field: str, from_expr, default=None) -> dict:
    return {"select": ["data", field], "from": from_expr,
            "default": default}


def exists_(cls: str, instance_id) -> dict:
    return {"exists": ref_(cls, instance_id)["ref"]}


def create_(cls: str, instance_id, data: dict) -> dict:
    return {"create": ref_(cls, instance_id)["ref"],
            "params": {"object": {"data": {"object": data}}}}


def update_(cls: str, instance_id, data: dict) -> dict:
    return {"update": ref_(cls, instance_id)["ref"],
            "params": {"object": {"data": {"object": data}}}}


def if_(cond, then, else_) -> dict:
    return {"if": cond, "then": then, "else": else_}


def do_(*exprs) -> dict:
    return {"do": list(exprs)}


def upsert(cls: str, instance_id, data: dict) -> dict:
    return if_(exists_(cls, instance_id),
               update_(cls, instance_id, data),
               create_(cls, instance_id, data))


def let_(bindings: dict, in_expr) -> dict:
    """Let(bindings, in) — the ordered wire form is an ARRAY of
    single-binding objects, so later bindings may reference earlier ones
    via ``var_`` (the q/let form internal.clj's create-tabby-let leans
    on for its evaluation-order probe)."""
    return {"let": [{k: v} for k, v in bindings.items()], "in": in_expr}


def var_(name: str) -> dict:
    return {"var": name}


def lambda_(param: str, expr) -> dict:
    return {"lambda": param, "expr": expr}


def map_(collection, param: str, expr) -> dict:
    """Map(lambda, collection) — the wire form carries the lambda under
    the ``map`` key and the collection alongside."""
    return {"map": lambda_(param, expr), "collection": collection}


def foreach_(collection, param: str, expr) -> dict:
    return {"foreach": lambda_(param, expr), "collection": collection}


def at_(ts, expr) -> dict:
    """At(ts, expr): evaluate ``expr`` against the snapshot at ``ts``
    (the temporal-read form monotonic.clj's read-at rides)."""
    return {"at": ts, "expr": expr}


def update_ref_(ref_expr, data: dict) -> dict:
    """Update through a computed ref expression (vs ``update_``'s
    literal class/id)."""
    return {"update": ref_expr,
            "params": {"object": {"data": {"object": data}}}}


TIME_NOW = {"time": "now"}


def strip_ts(ts):
    """Normalizes a transaction timestamp for string comparison: unwraps
    the ``{"@ts": ...}`` wire form and strips a trailing Z
    (monotonic.clj:51-59 — '...09Z' and '...09.143Z' don't compare as
    strings until the Z goes)."""
    if isinstance(ts, dict) and "@ts" in ts:
        ts = ts["@ts"]
    if isinstance(ts, str) and ts.endswith("Z"):
        return ts[:-1]
    return ts


def jitter_ts(ts, jitter_s: float, rng=None):
    """A timestamp up to ``jitter_s`` seconds before ``ts`` (the
    :at-query-jitter past-read monotonic.clj:118-121 uses). Stripped
    ISO-8601 strings are shifted properly; anything unparseable is
    returned as-is (an honest current-time read, never a fabrication)."""
    import datetime
    import random as _random
    rng = rng or _random
    if isinstance(ts, (int, float)) and not isinstance(ts, bool):
        return ts - rng.random() * jitter_s
    try:
        dt = datetime.datetime.fromisoformat(str(ts))
    except ValueError:
        return ts
    dt -= datetime.timedelta(seconds=rng.random() * jitter_s)
    out = dt.isoformat()
    return out


def _names(page):
    """Flattens a paginate/map result to a plain list (the ``{"data":
    [...]}`` page wrapper or a bare list)."""
    if isinstance(page, dict):
        page = page.get("data", [])
    return list(page or [])


class FaunaDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """FaunaDB lifecycle (faunadb/auto.clj): package install, yml
    config, init on the primary, join everywhere else."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing faunadb", node)
        os_setup.install(["faunadb"])
        cu.write_file(config_yml(test, node), YML)
        control.exec_("service", "faunadb", "start")
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            control.exec_(control.lit(
                "faunadb-admin init -r replica-1 2>/dev/null || true"))
        core.synchronize(test, timeout_s=600.0)
        if node != primary:
            control.exec_(control.lit(
                f"faunadb-admin join -r replica-1 {primary} "
                f"2>/dev/null || true"))
        core.synchronize(test, timeout_s=600.0)
        cu.await_tcp_port(PORT, host=node, timeout_s=300.0)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf("/var/lib/faunadb/*")

    def start(self, test, node):
        control.exec_("service", "faunadb", "start")

    def kill(self, test, node):
        control.exec_(control.lit(
            "service faunadb stop >/dev/null 2>&1 || true"))
        cu.grepkill("faunadb")

    def pause(self, test, node):
        cu.grepkill("faunadb", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("faunadb", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class FaunaClient(Client):
    """register/set/bank over single-query FQL transactions."""

    def __init__(self, timeout_s: float = 10.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return type(self)(self.timeout_s, node)

    def _query(self, expr):
        auth = base64.b64encode(f"{SECRET}:".encode()).decode()
        out = http_json(f"http://{self.node}:{PORT}/", expr,
                        timeout_s=self.timeout_s,
                        headers={"Authorization": f"Basic {auth}"})
        if isinstance(out, dict) and "errors" in out:
            raise FaunaError(out["errors"])
        return out.get("resource") if isinstance(out, dict) else out

    def setup(self, test):
        for cls in ("registers", "accounts", "elements", "adya"):
            try:
                self._query({"create_class": {"object": {"name": cls}}})
            except FaunaError:
                pass  # already exists
        if test.get("fauna_internal"):
            # cats + the by-type [ref, name] index (internal.clj:60-69)
            for expr in (
                    {"create_class": {"object": {"name": "cats"}}},
                    {"create_index": {"object": {
                        "name": "cats_by_type",
                        "source": {"@ref": "classes/cats"},
                        "terms": [{"field": ["data", "type"]}],
                        "values": [{"field": ["ref"]},
                                   {"field": ["data", "name"]}]}}}):
                try:
                    self._query(expr)
                except FaunaError:
                    pass
        try:
            # enumeration index for the set workload's whole reads
            # (faunadb/set.clj builds the same all-elements index)
            self._query({"create_index": {"object": {
                "name": "all_elements",
                "source": {"@ref": "classes/elements"},
                "values": [{"field": ["data", "elem"]}]}}})
        except FaunaError:
            pass
        try:
            # pages workload: per-key groups read through cursor-paged
            # index matches (pages.clj's by-key index)
            self._query({"create_index": {"object": {
                "name": "pages_by_key",
                "source": {"@ref": "classes/elements"},
                "terms": [{"field": ["data", "key"]}],
                "values": [{"field": ["data", "value"]}]}}})
        except FaunaError:
            pass
        try:
            # pair-term index: the adya probe's PREDICATE read (a phantom
            # -permitting DB must be caught, so the guard reads the whole
            # pair through the index, not two concrete refs — g2.clj)
            self._query({"create_index": {"object": {
                "name": "adya_by_pair",
                "source": {"@ref": "classes/adya"},
                "terms": [{"field": ["data", "pair"]}]}}})
        except FaunaError:
            pass
        for a in test.get("accounts", []):
            try:
                self._query(create_("accounts", a, {"balance": 10}))
            except FaunaError:
                pass

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if test.get("fauna_monotonic"):
                return self._monotonic_invoke(test, op)
            if test.get("fauna_multimonotonic"):
                return self._multimonotonic_invoke(test, op)
            if test.get("fauna_internal"):
                return self._internal_invoke(test, op)
            if f == "read" and v is None and test.get("accounts"):
                # ONE query = one transaction: an object of selects reads
                # every balance in the same snapshot (per-account queries
                # would interleave with transfers → false wrong-total)
                expr = {"object": {
                    str(a): select_data("balance", get_("accounts", a),
                                        default=0)
                    for a in test.get("accounts")}}
                balances = self._query(expr) or {}
                return {**op, "type": "ok",
                        "value": {int(a): int(b or 0)
                                  for a, b in balances.items()}}
            if f == "transfer":
                return self._transfer(op)
            if test.get("pages") and f == "add":
                k, group = v
                # ONE query = one transaction: the whole group inserts
                # atomically (pages.clj:48-56)
                self._query(do_(*[
                    {"create": {"@ref": "classes/elements"},
                     "params": {"object": {"data": {"object": {
                         "key": int(k), "value": int(el)}}}}}
                    for el in group]))
                return {**op, "type": "ok"}
            if test.get("pages") and f == "read":
                k, _ = v
                # page through the key's index match with small cursored
                # pages — separate queries, which is exactly the
                # isolation surface under test (pages.clj query-all)
                match = {"match": {"index": {"@ref": "indexes/pages_by_key"}},
                         "terms": int(k)}
                out: list = []
                after = None
                while True:
                    q = {"paginate": match, "size": 4}
                    if after is not None:
                        q["after"] = after
                    res = self._query(q)
                    res = res if isinstance(res, dict) else {}
                    out += [int(x) for x in res.get("data", [])]
                    after = res.get("after")
                    if after is None:
                        return {**op, "type": "ok", "value": [k, out]}
            if f == "add":
                self._query(upsert("elements", int(v), {"elem": int(v)}))
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                # set whole-read: paginate the all-elements index in one
                # query/transaction (faunadb/set.clj's read; the pages
                # workload stresses exactly this surface)
                out = self._query({
                    "paginate": {"match": {"index":
                                           {"@ref": "indexes/all_elements"}}},
                    "size": 100000})
                elems = (out.get("data", []) if isinstance(out, dict)
                         else (out or []))
                return {**op, "type": "ok",
                        "value": sorted(int(e) for e in elems)}
            if f == "insert":
                # adya G2 probe: PREDICATE-read the pair through the
                # adya_by_pair index and create our cell only if it is
                # empty — one FQL If is one strictly-serializable
                # transaction, and the index match (not item reads of
                # concrete refs) is what makes a phantom-permitting DB
                # fail the probe (faunadb/g2.clj shape)
                pair, uid, cell = v
                pair_match = {"match": {"index":
                                        {"@ref": "indexes/adya_by_pair"}},
                              "terms": int(pair)}
                out = self._query(if_(
                    {"is_empty": {"paginate": pair_match}},
                    do_(create_("adya", f"{int(pair)}-{cell}",
                                {"uid": int(uid), "pair": int(pair)}),
                        True),
                    False))
                if out is True:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": ["pair-occupied"]}
            if f == "read":
                k, _ = v
                out = self._query(select_data("v", get_("registers", k)))
                return {**op, "type": "ok",
                        "value": [k, int(out) if out is not None else None]}
            if f == "write":
                k, val = v
                self._query(upsert("registers", k, {"v": int(val)}))
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                out = self._query(if_(
                    {"equals": [select_data("v", get_("registers", k)),
                                int(old)]},
                    do_(update_("registers", k, {"v": int(new)}), True),
                    False))
                return {**op, "type": "ok" if out is True else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except FaunaError as e:
            # instance not found on a REGISTER read → empty register
            # (bank reads carry value None — not unpackable). A pages
            # read must NOT take this recovery: its value shape matches,
            # but a not-found there means the index is missing, and a
            # fabricated ok-empty read would mask pagination anomalies
            # behind a trivially-valid verdict. Multimonotonic reads
            # also carry a list value (of keys) — the recovery would
            # unpack garbage (or crash), so those are gated out too.
            if f == "read" and isinstance(v, (list, tuple)) \
                    and not test.get("pages") \
                    and not test.get("fauna_multimonotonic") \
                    and e.not_found():
                k, _ = v
                return {**op, "type": "ok", "value": [k, None]}
            kind = "fail" if f in ("read", "read-at") else "info"
            # surface not-found as its own tagged element so the
            # monotonic suite's not-found checker can see it
            err = (["fauna", "not-found", str(e)] if e.not_found()
                   else ["fauna", str(e)])
            return {**op, "type": kind, "error": err}
        except urllib.error.HTTPError as e:
            kind = "fail" if f in ("read", "read-at") else "info"
            return {**op, "type": kind,
                    "error": ["http", e.code, http_error_json(e)]}
        except NET_ERRORS as e:
            kind = "fail" if f in ("read", "read-at") else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    # -- monotonic (monotonic.clj:93-141) -------------------------------

    def _monotonic_invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        r = ("registers", 0)
        if f == "inc":
            # one txn: [now, if exists then (remember v; v:=v+1; v) else
            # (create 1; 0)] — returns the PRE-increment value with the
            # txn time (monotonic.clj:99-110)
            out = self._query([
                TIME_NOW,
                if_(exists_(*r),
                    let_({"v": select_data("value", get_(*r))},
                         do_(update_(*r, {"value": {"add": [var_("v"), 1]}}),
                             var_("v"))),
                    do_(create_(*r, {"value": 1}), 0))])
            ts, val = out
            return {**op, "type": "ok", "value": [strip_ts(ts), val]}
        if f == "read":
            out = self._query([
                TIME_NOW,
                if_(exists_(*r), select_data("value", get_(*r)), 0)])
            ts, val = out
            return {**op, "type": "ok", "value": [strip_ts(ts), val]}
        if f == "read-at":
            ts = (v or [None])[0]
            if ts is None:
                now = self._query(TIME_NOW)
                ts = jitter_ts(strip_ts(now),
                               test.get("at_query_jitter", 1.0))
            # a stripped ISO string must go back over the wire as a
            # timestamp VALUE, not a bare string — re-tag through Time()
            ts_expr = {"time": f"{ts}Z"} if isinstance(ts, str) else ts
            out = self._query([
                ts_expr, at_(ts_expr, if_(exists_(*r),
                                          select_data("value", get_(*r)), 0))])
            ts2, val = out
            return {**op, "type": "ok", "value": [strip_ts(ts2), val]}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    # -- multimonotonic (multimonotonic.clj:85-105) ----------------------

    def _multimonotonic_invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if f == "write":
            # blind writes: no read locks, maximum throughput
            # (multimonotonic.clj:90-95)
            self._query(do_(*[upsert("registers", int(k), {"value": int(x)})
                              for k, x in sorted((v or {}).items())]))
            return {**op, "type": "ok", "value": v}
        if f == "read":
            ks = list(v or [])
            out = self._query([
                TIME_NOW,
                [if_(exists_("registers", int(k)), get_("registers", int(k)),
                     None)
                 for k in ks]])
            ts, instances = out
            regs = {}
            for k, inst in zip(ks, instances or []):
                if isinstance(inst, dict):
                    data = inst.get("data") or {}
                    regs[k] = {"value": data.get("value"),
                               "ts": strip_ts(inst.get("ts"))}
            return {**op, "type": "ok",
                    "value": {"ts": strip_ts(ts), "registers": regs}}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    # -- internal (internal.clj:71-133) ----------------------------------

    CATS_INDEX = {"@ref": "indexes/cats_by_type"}

    def _match_cats(self, cat_type: str) -> dict:
        """First 1024 cat [ref, name] pairs of a type through the index
        (internal.clj:33-39)."""
        return {"paginate": {"match": {"index": self.CATS_INDEX},
                             "terms": cat_type},
                "size": 1024}

    def _match_names(self, cat_type: str) -> dict:
        """Just the names of a type — a Map(lambda) over the page's
        [ref, name] pairs (internal.clj:33-39)."""
        return map_({"select": ["data"], "from": self._match_cats(cat_type)},
                    "row", {"select": [1], "from": var_("row")})

    def _internal_invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if f == "reset":
            # delete every cat of both types, guarded per-ref because
            # indices need not be serializable (internal.clj:41-53)
            self._query(do_(*[
                foreach_({"select": ["data"], "from": self._match_cats(t)},
                         "row",
                         if_({"exists": {"select": [0],
                                         "from": var_("row")}},
                             {"delete": {"select": [0],
                                         "from": var_("row")}},
                             None))
                for t in ("tabby", "calico")]))
            return {**op, "type": "ok"}
        if f in ("create-tabby-let", "create-tabby-obj",
                 "create-tabby-arr"):
            name = f"cat-{int(v)}"
            create = create_("cats", int(v), {"type": "tabby",
                                              "name": name})
            if f == "create-tabby-let":
                # at(now) observes the txn's own mutations — the let
                # binds in source order, the result object is permuted
                # (internal.clj:80-96)
                expr = let_({"t": TIME_NOW},
                            let_({"tabbies0": at_(var_("t"),
                                                  self._match_names("tabby")),
                                  "tabby": create,
                                  "tabbies1": at_(var_("t"),
                                                  self._match_names("tabby"))},
                                 {"object": {"tabbies-1": var_("tabbies1"),
                                             "tabby": name,
                                             "tabbies-0": var_("tabbies0")}}))
                out = self._query(expr) or {}
                out = dict(out)
            elif f == "create-tabby-obj":
                # object-literal composition, evaluated in key order
                # (internal.clj:98-113); keys chosen so declaration
                # order ≠ alphabetical order
                out = self._query({"object": {
                    "c": self._match_names("tabby"),
                    "a": create,
                    "b": self._match_names("tabby")}}) or {}
                out = {"tabbies-0": out.get("c"), "tabby": name,
                       "tabbies-1": out.get("b")}
            else:
                # array composition (internal.clj:115-121)
                out = self._query([self._match_names("tabby"), create,
                                   self._match_names("tabby")]) or []
                out = {"tabbies-0": out[0] if len(out) > 0 else [],
                       "tabby": name,
                       "tabbies-1": out[2] if len(out) > 2 else []}
            out["tabbies-0"] = _names(out.get("tabbies-0"))
            out["tabbies-1"] = _names(out.get("tabbies-1"))
            out["tabby"] = name
            return {**op, "type": "ok", "value": out}
        if f == "change-type":
            # retype the first tabby, re-read both sets — one txn
            # (internal.clj:123-132)
            expr = let_(
                {"page": {"paginate": {"match": {"index": self.CATS_INDEX},
                                       "terms": "tabby"},
                          "size": 1}},
                [if_({"non_empty": {"select": ["data"], "from": var_("page")}},
                     do_(update_ref_({"select": ["data", 0, 0],
                                      "from": var_("page")},
                                     {"type": "calico"}),
                         {"select": ["data", 0, 1], "from": var_("page")}),
                     None),
                 self._match_names("tabby"),
                 self._match_names("calico")])
            out = self._query(expr) or [None, [], []]
            name, tabbies, calicos = (list(out) + [None, [], []])[:3]
            return {**op, "type": "ok",
                    "value": [name, _names(tabbies), _names(calicos)]}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    def _transfer(self, op):
        """One transactional Do: guard both balances, move the amount
        (faunadb/bank.clj's shape — the whole expression is one txn)."""
        t = op.get("value") or {}
        frm, to, amount = t.get("from"), t.get("to"), int(t.get("amount", 0))
        b_from = select_data("balance", get_("accounts", frm), default=0)
        b_to = select_data("balance", get_("accounts", to), default=0)
        out = self._query(if_(
            {"lt": [{"subtract": [b_from, amount]}, 0]},
            False,
            do_(update_("accounts", frm,
                        {"balance": {"subtract": [b_from, amount]}}),
                update_("accounts", to,
                        {"balance": {"add": [b_to, amount]}}),
                True)))
        if out is True:
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": ["negative"]}


class FaunaError(Exception):
    """A FaunaDB ``errors`` response body."""

    def __init__(self, errors):
        super().__init__(str(errors))
        self.errors = errors

    def not_found(self) -> bool:
        return any(e.get("code") == "instance not found"
                   for e in self.errors if isinstance(e, dict))


# ---------------------------------------------------------------------------
# Fake doubles for the monotonic / multimonotonic / internal workloads:
# a shared versioned store with a logical clock standing in for Fauna's
# temporal model (SURVEY.md §4 tier-2 cluster-free lifecycle tests)
# ---------------------------------------------------------------------------

class _FakeFaunaState:
    """Versioned registers + cats under one lock and logical clock."""

    def __init__(self):
        import threading
        self.lock = threading.Lock()
        self.clock = 0
        self.history: dict = {}  # key -> [(ts, value), ...] append-only
        self.cats: dict = {}     # name -> type

    def tick(self) -> int:
        self.clock += 1
        return self.clock


class _FakeFaunaClient(Client):
    """Shared base: linearizable by construction, so every fake-mode
    lifecycle run must come back valid."""

    def __init__(self, state: _FakeFaunaState | None = None):
        self.state = state or _FakeFaunaState()

    def open(self, test, node):
        return type(self)(self.state)

    def setup(self, test):
        pass


class FakeMonotonicFauna(_FakeFaunaClient):
    """Single increment-only register with temporal reads."""

    def invoke(self, test, op):
        import random
        s = self.state
        f = op.get("f")
        with s.lock:
            hist = s.history.setdefault(0, [])
            if f == "inc":
                ts = s.tick()
                pre = hist[-1][1] if hist else 0
                hist.append((ts, pre + 1))
                return {**op, "type": "ok", "value": [ts, pre]}
            if f == "read":
                ts = s.tick()
                return {**op, "type": "ok",
                        "value": [ts, hist[-1][1] if hist else 0]}
            if f == "read-at":
                ts = (op.get("value") or [None])[0]
                if ts is None:
                    ts = max(1, s.clock - random.randint(0, 3))
                val = 0
                for t, v in hist:
                    if t <= ts:
                        val = v
                return {**op, "type": "ok", "value": [ts, val]}
        return {**op, "type": "fail", "error": ["unknown-f", f]}


class FakeMultimonotonicFauna(_FakeFaunaClient):
    """Per-key increment-only registers, snapshot reads."""

    def invoke(self, test, op):
        s = self.state
        f, v = op.get("f"), op.get("value")
        with s.lock:
            if f == "write":
                ts = s.tick()
                for k, x in (v or {}).items():
                    s.history.setdefault(k, []).append((ts, x))
                return {**op, "type": "ok", "value": v}
            if f == "read":
                ts = s.tick()
                regs = {}
                for k in v or []:
                    hist = s.history.get(k)
                    if hist:
                        regs[k] = {"value": hist[-1][1], "ts": hist[-1][0]}
                return {**op, "type": "ok",
                        "value": {"ts": ts, "registers": regs}}
        return {**op, "type": "fail", "error": ["unknown-f", f]}


class FakeInternalFauna(_FakeFaunaClient):
    """Atomic cats-by-type mutations with in-transaction re-reads."""

    def invoke(self, test, op):
        s = self.state
        f, v = op.get("f"), op.get("value")

        def names(t):
            return sorted(n for n, typ in s.cats.items() if typ == t)

        with s.lock:
            if f == "reset":
                s.cats = {n: t for n, t in s.cats.items()
                          if t not in ("tabby", "calico")}
                return {**op, "type": "ok"}
            if f in ("create-tabby-let", "create-tabby-obj",
                     "create-tabby-arr"):
                name = f"cat-{int(v)}"
                before = names("tabby")
                s.cats[name] = "tabby"
                return {**op, "type": "ok",
                        "value": {"tabbies-0": before, "tabby": name,
                                  "tabbies-1": names("tabby")}}
            if f == "change-type":
                tabbies = names("tabby")
                name = tabbies[0] if tabbies else None
                if name is not None:
                    s.cats[name] = "calico"
                return {**op, "type": "ok",
                        "value": [name, names("tabby"), names("calico")]}
        return {**op, "type": "fail", "error": ["unknown-f", f]}


# ---------------------------------------------------------------------------
# Topology nemesis: grow/shrink the cluster through the membership
# machinery (topology.clj + nemesis.clj:74-139's topo-nemesis)
# ---------------------------------------------------------------------------

class FaunaTopology(_membership.State):
    """Membership State over faunadb-admin: models the cluster as
    ``{"replica_count": n, "nodes": [{"node", "state", "replica"}]}``
    (topology.clj:12-28), generates random add-node / remove-node
    transitions (topology.clj:103-138), and applies them with the
    reference's recipe — configure + start + join for adds
    (nemesis.clj:101-108), kill + wipe + remove-from-peer for removes
    (nemesis.clj:110-133)."""

    def __init__(self, replicas: int = 3, rng=None):
        import random as _random
        self.replicas = replicas
        self.rng = rng or _random.Random()
        self.topo: dict | None = None

    # -- topology.clj:12-28 ---------------------------------------------
    def _ensure_topo(self, test) -> dict:
        if self.topo is None:
            nodes = list(test.get("nodes") or [])
            k = min(self.replicas, max(1, len(nodes)))
            self.topo = {
                "replica_count": k,
                "nodes": [{"node": n, "state": "active",
                           "replica": f"replica-{i % k}"}
                          for i, n in enumerate(nodes)]}
        return self.topo

    def _active(self) -> list[dict]:
        return [n for n in (self.topo or {}).get("nodes", [])
                if n["state"] == "active"]

    # -- membership State protocol --------------------------------------
    def node_view(self, test, node):
        """``faunadb-admin status`` parsed to this node's member list;
        None when the output isn't a status table (e.g. dummy remote)."""
        from jepsen_tpu import control
        out = control.on(
            node, test,
            lambda: control.exec_(control.lit(
                "faunadb-admin status 2>/dev/null || true")))
        members = []
        for line in str(out or "").splitlines():
            parts = line.split()
            if len(parts) >= 3 and parts[1].startswith("replica-"):
                members.append({"node": parts[0], "replica": parts[1],
                                "state": parts[2].lower()})
        return members or None

    def merge_views(self, test, views):
        """Adopt the largest parseable view; absent any (fake mode), the
        model from applied transitions stands."""
        best = max((v for v in views.values() if v), key=len, default=None)
        if best is not None:
            topo = self._ensure_topo(test)
            by_name = {m["node"]: m for m in best}
            for n in topo["nodes"]:
                seen = by_name.get(n["node"])
                if seen is not None:
                    n["state"] = ("active" if seen["state"] in
                                  ("active", "up", "live") else seen["state"])
        return self

    def fs(self):
        return {"add-node", "remove-node"}

    def op(self, test):
        """A random feasible transition (topology.clj:158-183): add any
        test node not in the cluster, or remove a node whose replica
        keeps ≥1 member."""
        topo = self._ensure_topo(test)
        active = self._active()
        candidates = []
        absent = sorted(set(test.get("nodes") or [])
                        - {n["node"] for n in topo["nodes"]})
        if absent and active:
            node = self.rng.choice(absent)
            candidates.append({
                "type": "info", "f": "add-node",
                "value": {"node": node,
                          "join": self.rng.choice(active)["node"]}})
        by_replica: dict = {}
        for n in active:
            by_replica.setdefault(n["replica"], []).append(n["node"])
        removable = sorted(n for ns in by_replica.values() if len(ns) > 1
                           for n in ns)
        if removable:
            candidates.append({"type": "info", "f": "remove-node",
                               "value": self.rng.choice(removable)})
        if not candidates:
            return "pending"
        return self.rng.choice(candidates)

    def invoke(self, test, op):
        from jepsen_tpu import control
        topo = self._ensure_topo(test)
        f, v = op.get("f"), op.get("value")
        if f == "add-node":
            node, join = v["node"], v["join"]
            replica = f"replica-{self.rng.randrange(topo['replica_count'])}"

            def _add():
                cu.write_file(config_yml(test, node), YML)
                control.exec_("service", "faunadb", "start")
                control.exec_(control.lit(
                    f"faunadb-admin join -r {replica} {join} "
                    f"2>/dev/null || true"))
            control.on(node, test, _add)
            topo["nodes"].append({"node": node, "state": "active",
                                  "replica": replica})
            return ["added", v]
        if f == "remove-node":
            # stop-then-remove: the reference found live removal
            # untrodden ground (nemesis.clj:110-117)
            control.on(v, test, lambda: (
                control.exec_(control.lit(
                    "service faunadb stop >/dev/null 2>&1 || true")),
                cu.rm_rf("/var/lib/faunadb/*")))
            peers = [n["node"] for n in self._active() if n["node"] != v]
            if peers:
                peer = self.rng.choice(peers)
                control.on(peer, test, lambda: control.exec_(control.lit(
                    f"faunadb-admin remove {v} 2>/dev/null || true")))
            topo["nodes"] = [n for n in topo["nodes"] if n["node"] != v]
            return ["removed", v]
        return ["noop", f]

    def resolve(self, test):
        return self

    def resolve_op(self, test, pending_pair):
        """Transitions apply synchronously (the reference resets its
        topology atom right in invoke, nemesis.clj:135-137)."""
        return self

    def teardown(self, test):
        pass


def topology_fault_package(opts: dict,
                           topo: "FaunaTopology | None" = None) -> dict:
    """--fault topology: the membership package over FaunaTopology."""
    from jepsen_tpu.nemesis import membership
    return membership.package(topo or FaunaTopology(),
                              interval=opts.get("interval", 10.0))


class ReplicaPartitionNemesis:
    """Applies topology-derived grudges (faunadb/nemesis.clj:29-55: the
    partition vocabulary no generic package can produce — the GRUDGE is
    computed by the generator from the tracked replica assignments and
    carried in the op value)."""

    def fs(self):
        return {"start-partition-replica", "stop-partition-replica"}

    def setup(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        net = test.get("net")
        if f == "start-partition-replica":
            v = op.get("value") or {}
            grudge = v.get("grudge") or {}
            if net is not None:
                net.drop_all(test, grudge)
            return {**op, "type": "info",
                    "value": ["isolated", v.get("partition-type"), grudge]}
        if f == "stop-partition-replica":
            if net is not None:
                net.heal(test)
            return {**op, "type": "info", "value": ["network-healed"]}
        return {**op, "type": "info", "value": ["unknown-f", f]}

    def teardown(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)


def replica_partition_ops(topo: "FaunaTopology", rng=None):
    """Generator fn emitting intra- or inter-replica partition starts
    from the CURRENT topology (nemesis.clj:29-55 single-node /
    intra-replica / inter-replica trio; single-node rides the generic
    partition package here, so this fn carries the replica-aware two)."""
    import random as _random

    from jepsen_tpu import nemesis as nem
    r = rng or _random.Random()

    def start_op(test=None, ctx=None):
        t = topo._ensure_topo(test or {})
        by_rep: dict[str, list] = {}
        for n in t["nodes"]:
            if n["state"] == "active":
                by_rep.setdefault(n["replica"], []).append(n["node"])
        kinds = []
        if any(len(ns) >= 2 for ns in by_rep.values()):
            kinds.append("intra")
        if len(by_rep) >= 2:
            kinds.append("inter")
        if not kinds:
            return {"type": "info", "f": "stop-partition-replica",
                    "value": None}   # degenerate topology: nothing to cut
        kind = r.choice(kinds)
        if kind == "intra":
            # split INSIDE one replica; other replicas stay connected to
            # both halves (nemesis.clj:29-40)
            replica, nodes = r.choice(
                [(rep, ns) for rep, ns in sorted(by_rep.items())
                 if len(ns) >= 2])
            halves = nem.bisect(r.sample(nodes, len(nodes)))
            grudge = nem.complete_grudge(halves)
            ptype = ["intra-replica", replica]
        else:
            # divide replica GROUPS into two sides (nemesis.clj:42-55)
            groups = [ns for _, ns in sorted(by_rep.items())]
            r.shuffle(groups)
            a, b = nem.bisect(groups)
            grudge = nem.complete_grudge(
                [[n for g in a for n in g], [n for g in b for n in g]])
            ptype = ["inter-replica"]
        return {"type": "info", "f": "start-partition-replica",
                "value": {"grudge": grudge, "partition-type": ptype}}

    return start_op


def replica_partition_package(opts: dict, topo: "FaunaTopology") -> dict:
    """--fault partition-replica: topology-aware partitions, composable
    with the topology membership nemesis (the reference's full-nemesis
    runs them together, nemesis.clj:172-186)."""
    from jepsen_tpu import generator as gen
    interval = opts.get("interval", 10.0)
    g = gen.stagger(interval, gen.cycle(gen.Seq([
        gen.Fn(replica_partition_ops(topo)),
        {"type": "info", "f": "stop-partition-replica", "value": None},
    ])))
    return {
        "nemesis": ReplicaPartitionNemesis(),
        "generator": g,
        "final_generator": gen.Seq([
            {"type": "info", "f": "stop-partition-replica",
             "value": None}]),
        "perf": {"name": "partition-replica",
                 "fs": {"start-partition-replica",
                        "stop-partition-replica"},
                 "start": {"start-partition-replica"},
                 "stop": {"stop-partition-replica"}},
    }


SUPPORTED_WORKLOADS = ("register", "bank", "set", "adya", "pages",
                       "monotonic", "multimonotonic", "internal")

FAUNA_WORKLOADS = {"monotonic", "multimonotonic", "internal"}

FAKE_CLIENTS = {"monotonic": FakeMonotonicFauna,
                "multimonotonic": FakeMultimonotonicFauna,
                "internal": FakeInternalFauna}


def _extra_workloads() -> dict:
    from jepsen_tpu.workloads import (fauna_internal, fauna_monotonic,
                                      fauna_multimonotonic)
    return {"monotonic": fauna_monotonic.workload,
            "multimonotonic": fauna_multimonotonic.workload,
            "internal": fauna_internal.workload}


def faunadb_test(opts_dict: dict | None = None) -> dict:
    o = dict(opts_dict or {})
    workload_name = o.get("workload") or SUPPORTED_WORKLOADS[0]
    fake_client = FAKE_CLIENTS.get(workload_name)
    # one topology shared by the membership nemesis and the
    # replica-aware partitioner, so partitions cut along whatever
    # replica assignments the topology transitions have produced
    topo = FaunaTopology()
    return build_suite_test(
        o, db_name="faunadb",
        supported_workloads=SUPPORTED_WORKLOADS,
        extra_workloads=_extra_workloads(),
        fake_client=fake_client,
        fault_packages={
            "topology": lambda opts: topology_fault_package(opts, topo),
            "partition-replica":
                lambda opts: replica_partition_package(opts, topo)},
        make_real=lambda o: {"db": FaunaDB(), "client": FaunaClient(),
                             "os": Debian()})


main_all = standard_test_all(faunadb_test, SUPPORTED_WORKLOADS,
                             name="jepsen-faunadb")

main = cli.single_test_cmd(
    standard_test_fn(faunadb_test),
    standard_opt_fn(SUPPORTED_WORKLOADS, extra_faults=("topology", "partition-replica")),
    name="jepsen-faunadb")


if __name__ == "__main__":
    import sys
    sys.exit(main())
