"""FaunaDB test suite (reference: faunadb/src/jepsen/faunadb/ — a
Calvin-style distributed transactional database; the reference probes
registers, bank transfers, set membership (pages), and monotonicity
through the JVM driver).

Every FaunaDB query is a single transaction POSTed as a JSON-encoded
FQL expression to port 8443 with HTTP Basic auth (the cluster secret as
username) — so each workload op here is one ``http_json`` call carrying
a composed expression tree: register CAS is ``If(Equals(Select(...),
old), Update(...), false)`` evaluated atomically server-side
(faunadb/register.clj's cas shape), bank transfers are a ``Do`` of two
guarded updates, set adds create one instance per element.

DB automation per faunadb/auto.clj: install the ``faunadb`` apt
package, write /etc/faunadb.yml with this node's addresses, start the
service, ``faunadb-admin init`` on the primary and ``join`` elsewhere.
"""
from __future__ import annotations

import base64
import logging
import urllib.error

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_error_json, http_json

logger = logging.getLogger("jepsen.faunadb")

PORT = 8443
SECRET = "secret"
YML = "/etc/faunadb.yml"
LOG_FILE = "/var/log/faunadb/core.log"


def config_yml(test: dict, node: str) -> str:
    """/etc/faunadb.yml (faunadb/auto.clj:160-196 shape)."""
    return "\n".join([
        f"auth_root_key: {SECRET}",
        "cluster_name: jepsen",
        f"network_broadcast_address: {node}",
        "network_datacenter_name: replica-1",
        f"network_host_id: {node}",
        "network_listen_address: 0.0.0.0",
        "storage_data_path: /var/lib/faunadb",
        "log_path: /var/log/faunadb",
        "",
    ])


# -- FQL JSON expression builders (the v2 JSON wire forms the JVM driver
# -- emits; each helper returns a plain dict ready to POST) -----------------

def ref_(cls: str, instance_id) -> dict:
    return {"ref": {"@ref": f"classes/{cls}/{instance_id}"}}


def get_(cls: str, instance_id) -> dict:
    return {"get": ref_(cls, instance_id)["ref"]}


def select_data(field: str, from_expr, default=None) -> dict:
    return {"select": ["data", field], "from": from_expr,
            "default": default}


def exists_(cls: str, instance_id) -> dict:
    return {"exists": ref_(cls, instance_id)["ref"]}


def create_(cls: str, instance_id, data: dict) -> dict:
    return {"create": ref_(cls, instance_id)["ref"],
            "params": {"object": {"data": {"object": data}}}}


def update_(cls: str, instance_id, data: dict) -> dict:
    return {"update": ref_(cls, instance_id)["ref"],
            "params": {"object": {"data": {"object": data}}}}


def if_(cond, then, else_) -> dict:
    return {"if": cond, "then": then, "else": else_}


def do_(*exprs) -> dict:
    return {"do": list(exprs)}


def upsert(cls: str, instance_id, data: dict) -> dict:
    return if_(exists_(cls, instance_id),
               update_(cls, instance_id, data),
               create_(cls, instance_id, data))


class FaunaDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """FaunaDB lifecycle (faunadb/auto.clj): package install, yml
    config, init on the primary, join everywhere else."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing faunadb", node)
        os_setup.install(["faunadb"])
        cu.write_file(config_yml(test, node), YML)
        control.exec_("service", "faunadb", "start")
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            control.exec_(control.lit(
                "faunadb-admin init -r replica-1 2>/dev/null || true"))
        core.synchronize(test, timeout_s=600.0)
        if node != primary:
            control.exec_(control.lit(
                f"faunadb-admin join -r replica-1 {primary} "
                f"2>/dev/null || true"))
        core.synchronize(test, timeout_s=600.0)
        cu.await_tcp_port(PORT, host=node, timeout_s=300.0)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf("/var/lib/faunadb/*")

    def start(self, test, node):
        control.exec_("service", "faunadb", "start")

    def kill(self, test, node):
        control.exec_(control.lit(
            "service faunadb stop >/dev/null 2>&1 || true"))
        cu.grepkill("faunadb")

    def pause(self, test, node):
        cu.grepkill("faunadb", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("faunadb", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class FaunaClient(Client):
    """register/set/bank over single-query FQL transactions."""

    def __init__(self, timeout_s: float = 10.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return type(self)(self.timeout_s, node)

    def _query(self, expr):
        auth = base64.b64encode(f"{SECRET}:".encode()).decode()
        out = http_json(f"http://{self.node}:{PORT}/", expr,
                        timeout_s=self.timeout_s,
                        headers={"Authorization": f"Basic {auth}"})
        if isinstance(out, dict) and "errors" in out:
            raise FaunaError(out["errors"])
        return out.get("resource") if isinstance(out, dict) else out

    def setup(self, test):
        for cls in ("registers", "accounts", "elements", "adya"):
            try:
                self._query({"create_class": {"object": {"name": cls}}})
            except FaunaError:
                pass  # already exists
        try:
            # enumeration index for the set workload's whole reads
            # (faunadb/set.clj builds the same all-elements index)
            self._query({"create_index": {"object": {
                "name": "all_elements",
                "source": {"@ref": "classes/elements"},
                "values": [{"field": ["data", "elem"]}]}}})
        except FaunaError:
            pass
        try:
            # pages workload: per-key groups read through cursor-paged
            # index matches (pages.clj's by-key index)
            self._query({"create_index": {"object": {
                "name": "pages_by_key",
                "source": {"@ref": "classes/elements"},
                "terms": [{"field": ["data", "key"]}],
                "values": [{"field": ["data", "value"]}]}}})
        except FaunaError:
            pass
        try:
            # pair-term index: the adya probe's PREDICATE read (a phantom
            # -permitting DB must be caught, so the guard reads the whole
            # pair through the index, not two concrete refs — g2.clj)
            self._query({"create_index": {"object": {
                "name": "adya_by_pair",
                "source": {"@ref": "classes/adya"},
                "terms": [{"field": ["data", "pair"]}]}}})
        except FaunaError:
            pass
        for a in test.get("accounts", []):
            try:
                self._query(create_("accounts", a, {"balance": 10}))
            except FaunaError:
                pass

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "read" and v is None and test.get("accounts"):
                # ONE query = one transaction: an object of selects reads
                # every balance in the same snapshot (per-account queries
                # would interleave with transfers → false wrong-total)
                expr = {"object": {
                    str(a): select_data("balance", get_("accounts", a),
                                        default=0)
                    for a in test.get("accounts")}}
                balances = self._query(expr) or {}
                return {**op, "type": "ok",
                        "value": {int(a): int(b or 0)
                                  for a, b in balances.items()}}
            if f == "transfer":
                return self._transfer(op)
            if test.get("pages") and f == "add":
                k, group = v
                # ONE query = one transaction: the whole group inserts
                # atomically (pages.clj:48-56)
                self._query(do_(*[
                    {"create": {"@ref": "classes/elements"},
                     "params": {"object": {"data": {"object": {
                         "key": int(k), "value": int(el)}}}}}
                    for el in group]))
                return {**op, "type": "ok"}
            if test.get("pages") and f == "read":
                k, _ = v
                # page through the key's index match with small cursored
                # pages — separate queries, which is exactly the
                # isolation surface under test (pages.clj query-all)
                match = {"match": {"index": {"@ref": "indexes/pages_by_key"}},
                         "terms": int(k)}
                out: list = []
                after = None
                while True:
                    q = {"paginate": match, "size": 4}
                    if after is not None:
                        q["after"] = after
                    res = self._query(q)
                    res = res if isinstance(res, dict) else {}
                    out += [int(x) for x in res.get("data", [])]
                    after = res.get("after")
                    if after is None:
                        return {**op, "type": "ok", "value": [k, out]}
            if f == "add":
                self._query(upsert("elements", int(v), {"elem": int(v)}))
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                # set whole-read: paginate the all-elements index in one
                # query/transaction (faunadb/set.clj's read; the pages
                # workload stresses exactly this surface)
                out = self._query({
                    "paginate": {"match": {"index":
                                           {"@ref": "indexes/all_elements"}}},
                    "size": 100000})
                elems = (out.get("data", []) if isinstance(out, dict)
                         else (out or []))
                return {**op, "type": "ok",
                        "value": sorted(int(e) for e in elems)}
            if f == "insert":
                # adya G2 probe: PREDICATE-read the pair through the
                # adya_by_pair index and create our cell only if it is
                # empty — one FQL If is one strictly-serializable
                # transaction, and the index match (not item reads of
                # concrete refs) is what makes a phantom-permitting DB
                # fail the probe (faunadb/g2.clj shape)
                pair, uid, cell = v
                pair_match = {"match": {"index":
                                        {"@ref": "indexes/adya_by_pair"}},
                              "terms": int(pair)}
                out = self._query(if_(
                    {"is_empty": {"paginate": pair_match}},
                    do_(create_("adya", f"{int(pair)}-{cell}",
                                {"uid": int(uid), "pair": int(pair)}),
                        True),
                    False))
                if out is True:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": ["pair-occupied"]}
            if f == "read":
                k, _ = v
                out = self._query(select_data("v", get_("registers", k)))
                return {**op, "type": "ok",
                        "value": [k, int(out) if out is not None else None]}
            if f == "write":
                k, val = v
                self._query(upsert("registers", k, {"v": int(val)}))
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                out = self._query(if_(
                    {"equals": [select_data("v", get_("registers", k)),
                                int(old)]},
                    do_(update_("registers", k, {"v": int(new)}), True),
                    False))
                return {**op, "type": "ok" if out is True else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except FaunaError as e:
            # instance not found on a REGISTER read → empty register
            # (bank reads carry value None — not unpackable). A pages
            # read must NOT take this recovery: its value shape matches,
            # but a not-found there means the index is missing, and a
            # fabricated ok-empty read would mask pagination anomalies
            # behind a trivially-valid verdict
            if f == "read" and isinstance(v, (list, tuple)) \
                    and not test.get("pages") and e.not_found():
                k, _ = v
                return {**op, "type": "ok", "value": [k, None]}
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["fauna", str(e)]}
        except urllib.error.HTTPError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind,
                    "error": ["http", e.code, http_error_json(e)]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def _transfer(self, op):
        """One transactional Do: guard both balances, move the amount
        (faunadb/bank.clj's shape — the whole expression is one txn)."""
        t = op.get("value") or {}
        frm, to, amount = t.get("from"), t.get("to"), int(t.get("amount", 0))
        b_from = select_data("balance", get_("accounts", frm), default=0)
        b_to = select_data("balance", get_("accounts", to), default=0)
        out = self._query(if_(
            {"lt": [{"subtract": [b_from, amount]}, 0]},
            False,
            do_(update_("accounts", frm,
                        {"balance": {"subtract": [b_from, amount]}}),
                update_("accounts", to,
                        {"balance": {"add": [b_to, amount]}}),
                True)))
        if out is True:
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": ["negative"]}


class FaunaError(Exception):
    """A FaunaDB ``errors`` response body."""

    def __init__(self, errors):
        super().__init__(str(errors))
        self.errors = errors

    def not_found(self) -> bool:
        return any(e.get("code") == "instance not found"
                   for e in self.errors if isinstance(e, dict))


SUPPORTED_WORKLOADS = ("register", "bank", "set", "adya", "pages")


def faunadb_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="faunadb",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": FaunaDB(), "client": FaunaClient(),
                             "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(faunadb_test),
    standard_opt_fn(SUPPORTED_WORKLOADS),
    name="jepsen-faunadb")


if __name__ == "__main__":
    import sys
    sys.exit(main())
