"""Minimal MySQL client-protocol implementation over stdlib sockets.

The reference's MySQL-family suites (percona/src/jepsen/percona.clj,
galera/src/jepsen/galera.clj, mysql-cluster/src/jepsen/mysql_cluster.clj,
tidb/src/tidb/sql.clj) all ride the JVM's jdbc/mysql driver; this module
is the TPU-framework equivalent wire client so those suites need no
third-party Python driver.

Implements the subset every suite needs: protocol-41 handshake with
``mysql_native_password`` auth (including auth-switch), ``COM_QUERY``
with text-protocol resultsets, OK/ERR/EOF packets, and ``COM_QUIT``.
Row values come back as Python strings (or None for SQL NULL) — callers
cast. No prepared statements, no compression, no TLS: test rigs connect
over the cluster's private network exactly like the reference's
conn-specs (percona.clj:102-109).
"""
from __future__ import annotations

import hashlib
import socket
import struct

CLIENT_LONG_PASSWORD = 0x0001
CLIENT_PROTOCOL_41 = 0x0200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x0008_0000
CLIENT_CONNECT_WITH_DB = 0x0008

UTF8_CHARSET = 33
MAX_PACKET = 16 * 1024 * 1024


class MySQLError(Exception):
    """Server ERR packet: ``.code`` (errno), ``.sqlstate``, ``.msg``."""

    def __init__(self, code: int, sqlstate: str, msg: str):
        super().__init__(f"({code}) [{sqlstate}] {msg}")
        self.code = code
        self.sqlstate = sqlstate
        self.msg = msg


def native_password_scramble(password: str, nonce: bytes) -> bytes:
    """SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw))) — mysql_native_password."""
    if not password:
        return b""
    h1 = hashlib.sha1(password.encode()).digest()
    h2 = hashlib.sha1(h1).digest()
    h3 = hashlib.sha1(nonce + h2).digest()
    return bytes(a ^ b for a, b in zip(h1, h3))


def _read_lenenc_int(buf: bytes, pos: int) -> tuple[int | None, int]:
    first = buf[pos]
    if first < 0xFB:
        return first, pos + 1
    if first == 0xFB:  # NULL in resultset rows
        return None, pos + 1
    if first == 0xFC:
        return struct.unpack_from("<H", buf, pos + 1)[0], pos + 3
    if first == 0xFD:
        return int.from_bytes(buf[pos + 1:pos + 4], "little"), pos + 4
    return struct.unpack_from("<Q", buf, pos + 1)[0], pos + 9


def _lenenc_bytes(data: bytes) -> bytes:
    n = len(data)
    if n < 0xFB:
        return bytes([n]) + data
    if n < 1 << 16:
        return b"\xfc" + struct.pack("<H", n) + data
    if n < 1 << 24:
        return b"\xfd" + n.to_bytes(3, "little") + data
    return b"\xfe" + struct.pack("<Q", n) + data


class MySQLConnection:
    """One authenticated connection; ``query`` returns rows or an OK tuple."""

    def __init__(self, host: str, port: int = 3306, user: str = "root",
                 password: str = "", database: str | None = None,
                 timeout_s: float = 10.0):
        self.host, self.port = host, port
        self._seq = 0
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        try:
            self._handshake(user, password, database)
        except BaseException:
            self.sock.close()
            raise

    # -- packet framing: 3-byte LE length + 1-byte sequence id ------------

    def _recv_exact(self, n: int) -> bytes:
        from jepsen_tpu.suites._wire import recv_exact
        return recv_exact(self.sock, n)

    def _read_packet(self) -> bytes:
        header = self._recv_exact(4)
        length = int.from_bytes(header[:3], "little")
        self._seq = (header[3] + 1) & 0xFF
        if length == 0xFFFFFF:
            # multi-packet continuation (payload >= 2^24-1 bytes): none of
            # the suites' statements come close; fail loudly over mis-framing
            raise ConnectionError(
                "multi-packet mysql responses unsupported (payload >= 16MB)")
        return self._recv_exact(length)

    def _send_packet(self, payload: bytes) -> None:
        self.sock.sendall(len(payload).to_bytes(3, "little")
                          + bytes([self._seq]) + payload)
        self._seq = (self._seq + 1) & 0xFF

    # -- handshake --------------------------------------------------------

    def _handshake(self, user: str, password: str,
                   database: str | None) -> None:
        greeting = self._read_packet()
        if greeting and greeting[0] == 0xFF:
            self._raise_err(greeting)
        if not greeting or greeting[0] != 0x0A:
            raise ConnectionError(
                f"unsupported mysql protocol version {greeting[:1]!r}")
        pos = 1
        end = greeting.index(b"\x00", pos)
        self.server_version = greeting[pos:end].decode("latin1")
        pos = end + 1
        pos += 4  # thread id
        nonce = greeting[pos:pos + 8]
        pos += 8 + 1  # auth-plugin-data-part-1 + filler
        caps = struct.unpack_from("<H", greeting, pos)[0]
        pos += 2
        plugin = "mysql_native_password"
        if len(greeting) > pos:
            pos += 1 + 2  # charset + status flags
            caps |= struct.unpack_from("<H", greeting, pos)[0] << 16
            pos += 2
            auth_len = greeting[pos]
            pos += 1 + 10  # auth data len + reserved
            if caps & CLIENT_SECURE_CONNECTION:
                extra = max(13, auth_len - 8)
                nonce += greeting[pos:pos + extra].rstrip(b"\x00")
                pos += extra
            if caps & CLIENT_PLUGIN_AUTH:
                end = greeting.find(b"\x00", pos)
                if end == -1:
                    end = len(greeting)
                plugin = greeting[pos:end].decode("latin1")

        client_caps = (CLIENT_LONG_PASSWORD | CLIENT_PROTOCOL_41
                       | CLIENT_TRANSACTIONS | CLIENT_SECURE_CONNECTION
                       | CLIENT_PLUGIN_AUTH)
        if database:
            client_caps |= CLIENT_CONNECT_WITH_DB
        auth = (native_password_scramble(password, nonce[:20])
                if plugin == "mysql_native_password" else b"")
        payload = (struct.pack("<IIB23x", client_caps, MAX_PACKET,
                               UTF8_CHARSET)
                   + user.encode() + b"\x00"
                   + _lenenc_bytes(auth)
                   + ((database.encode() + b"\x00") if database else b"")
                   + b"mysql_native_password\x00")
        self._send_packet(payload)

        resp = self._read_packet()
        if resp and resp[0] == 0xFE:  # AuthSwitchRequest
            end = resp.index(b"\x00", 1)
            new_plugin = resp[1:end].decode("latin1")
            if new_plugin != "mysql_native_password":
                raise ConnectionError(
                    f"unsupported auth plugin {new_plugin!r}")
            new_nonce = resp[end + 1:].rstrip(b"\x00")
            self._send_packet(native_password_scramble(password, new_nonce))
            resp = self._read_packet()
        if resp and resp[0] == 0xFF:
            self._raise_err(resp)
        if not resp or resp[0] != 0x00:
            raise ConnectionError(f"unexpected auth response {resp[:1]!r}")

    # -- queries ----------------------------------------------------------

    def _raise_err(self, packet: bytes) -> None:
        code = struct.unpack_from("<H", packet, 1)[0]
        sqlstate, msg_at = "", 3
        if len(packet) > 3 and packet[3:4] == b"#":
            sqlstate, msg_at = packet[4:9].decode("latin1"), 9
        raise MySQLError(code, sqlstate, packet[msg_at:].decode("utf8",
                                                                "replace"))

    def query(self, sql: str):
        """Runs one statement. Resultset → list of row tuples (str|None
        cells); otherwise → (affected_rows, last_insert_id)."""
        self._seq = 0
        self._send_packet(b"\x03" + sql.encode())
        first = self._read_packet()
        if first and first[0] == 0xFF:
            self._raise_err(first)
        if first and first[0] == 0x00:
            pos = 1
            affected, pos = _read_lenenc_int(first, pos)
            last_id, _pos = _read_lenenc_int(first, pos)
            return affected, last_id
        ncols, _ = _read_lenenc_int(first, 0)
        for _ in range(ncols):  # column definitions: skipped
            self._read_packet()
        self._expect_eof()
        rows = []
        while True:
            pkt = self._read_packet()
            if pkt and pkt[0] == 0xFF:
                self._raise_err(pkt)
            if pkt and pkt[0] == 0xFE and len(pkt) < 9:
                return rows
            row, pos = [], 0
            for _ in range(ncols):
                n, pos = _read_lenenc_int(pkt, pos)
                if n is None:  # 0xFB: SQL NULL
                    row.append(None)
                else:
                    row.append(pkt[pos:pos + n].decode("utf8", "replace"))
                    pos += n
            rows.append(tuple(row))

    def _expect_eof(self) -> None:
        pkt = self._read_packet()
        if not (pkt and pkt[0] == 0xFE and len(pkt) < 9):
            raise ConnectionError(f"expected EOF packet, got {pkt[:1]!r}")

    def close(self) -> None:
        try:
            self._seq = 0
            self._send_packet(b"\x01")  # COM_QUIT
        except OSError:
            pass
        finally:
            self.sock.close()
