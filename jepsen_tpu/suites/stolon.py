"""Stolon test suite (reference: stolon/src/jepsen/stolon/ — a
PostgreSQL HA manager: keepers wrap postgres instances, sentinels
elect a primary through an etcdv3 store, and proxies route clients to
the elected primary; the classic anomalies are lost updates across
failovers).

Workloads ride the shared Postgres-wire client against the local
node's stolon proxy (the reference clients also bind to their node,
stolon/client.clj). DB automation per stolon/db.clj: an etcd store
(reusing the etcd suite's automation), the stolon release tarball,
then keeper (``--uid pgN --pg-port 5433``), sentinel (with an
initial-cluster-spec json), and proxy daemons per node.
"""
from __future__ import annotations

import json
import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)
from jepsen_tpu.suites._pg_client import PGSuiteClient
from jepsen_tpu.suites.etcd import EtcdDB

logger = logging.getLogger("jepsen.stolon")

DEFAULT_VERSION = "0.17.0"
DIR = "/opt/stolon"
DATA_DIR = f"{DIR}/data"
CLUSTER_NAME = "jepsen"
PG_PORT = 5433       # keepers' postgres (stolon/db.clj:160)
PROXY_PORT = 25432   # stolon-proxy default listen port
ETCD_CLIENT_PORT = 2379
DB_NAME = "jepsen"
DB_USER = "postgres"
DB_PASS = "pw"


def tarball_url(version: str) -> str:
    return (f"https://github.com/sorintlab/stolon/releases/download/"
            f"v{version}/stolon-v{version}-linux-amd64.tar.gz")


def store_endpoints(test: dict) -> str:
    """The etcd store address list (stolon/db.clj:72-76)."""
    return ",".join(f"http://{n}:{ETCD_CLIENT_PORT}"
                    for n in (test.get("nodes") or []))


def pg_id(test: dict, node: str) -> str:
    """pg1..pgn (stolon/db.clj:129-133)."""
    return f"pg{(test.get('nodes') or [node]).index(node) + 1}"


def initial_cluster_spec(test: dict) -> dict:
    """Synchronous-replication cluster spec (stolon/db.clj:92-108)."""
    n = len(test.get("nodes") or [])
    return {
        "initMode": "new",
        "sleepInterval": "1s",
        "requestTimeout": "2s",
        "failInterval": "5s",
        "synchronousReplication": True,
        "proxyCheckInterval": "1s",
        "proxyTimeout": "3s",
        "maxStandbysPerSender": max(n - 1, 1),
        "minSynchronousStandbys": 1,
        "maxSynchronousStandbys": 1,
        "pgHBA": ["host all all 0.0.0.0/0 md5"],
    }


class StolonDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Stolon lifecycle: etcd store first, then sentinel (carrying the
    initial cluster spec), keeper, and proxy (stolon/db.clj:110-196)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version
        self.etcd = EtcdDB()

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        self.etcd.setup(test, node)
        os_setup.install(["postgresql"])
        control.exec_(control.lit(
            "service postgresql stop >/dev/null 2>&1 || true"))
        if not cu.file_exists(f"{DIR}/bin/stolon-keeper"):
            logger.info("%s: installing stolon %s", node, self.version)
            cu.install_archive(tarball_url(self.version), DIR)
            control.exec_(control.lit(
                f"d=$(find {DIR} -name stolon-keeper | head -1); "
                f"test -n \"$d\" && mkdir -p {DIR}/bin && "
                f"cp $(dirname $d)/stolon-* {DIR}/bin/ || true"))
        cu.mkdir(DATA_DIR)
        self.start_sentinel(test, node)
        self.start_keeper(test, node)
        core.synchronize(test, timeout_s=600.0)
        self.start_proxy(test, node)
        cu.await_tcp_port(PROXY_PORT, host=node, timeout_s=300.0)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            # the keepers init a bare postgres; create the jepsen
            # database through the proxy once it routes to the primary
            control.exec_(control.lit(
                f"PGPASSWORD={DB_PASS} psql -h {node} -p {PROXY_PORT} "
                f"-U {DB_USER} -d postgres -c 'CREATE DATABASE {DB_NAME}' "
                f"2>/dev/null || true"))
        core.synchronize(test, timeout_s=600.0)

    def start_sentinel(self, test, node):
        spec = f"{DIR}/init-spec.json"
        cu.write_file(json.dumps(initial_cluster_spec(test)), spec)
        return cu.start_daemon(
            {"logfile": f"{DIR}/sentinel.log",
             "pidfile": f"{DIR}/sentinel.pid", "chdir": DIR},
            f"{DIR}/bin/stolon-sentinel",
            "--cluster-name", CLUSTER_NAME,
            "--store-backend", "etcdv3",
            "--store-endpoints", store_endpoints(test),
            "--initial-cluster-spec", spec)

    def start_keeper(self, test, node):
        uid = pg_id(test, node)
        return cu.start_daemon(
            {"logfile": f"{DIR}/keeper.log",
             "pidfile": f"{DIR}/keeper.pid", "chdir": DIR},
            f"{DIR}/bin/stolon-keeper",
            "--cluster-name", CLUSTER_NAME,
            "--store-backend", "etcdv3",
            "--store-endpoints", store_endpoints(test),
            "--uid", uid,
            "--data-dir", f"{DATA_DIR}/{uid}",
            "--pg-su-password", DB_PASS,
            "--pg-repl-username", "repluser",
            "--pg-repl-password", DB_PASS,
            "--pg-listen-address", node,
            "--pg-port", str(PG_PORT),
            "--pg-bin-path", "/usr/lib/postgresql/*/bin")

    def start_proxy(self, test, node):
        return cu.start_daemon(
            {"logfile": f"{DIR}/proxy.log",
             "pidfile": f"{DIR}/proxy.pid", "chdir": DIR},
            f"{DIR}/bin/stolon-proxy",
            "--cluster-name", CLUSTER_NAME,
            "--store-backend", "etcdv3",
            "--store-endpoints", store_endpoints(test),
            "--listen-address", "0.0.0.0",
            "--port", str(PROXY_PORT))

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DATA_DIR)
        self.etcd.teardown(test, node)

    def start(self, test, node):
        self.start_sentinel(test, node)
        self.start_keeper(test, node)
        self.start_proxy(test, node)

    def kill(self, test, node):
        for name in ("stolon-proxy", "stolon-sentinel", "stolon-keeper",
                     "postgres"):
            cu.grepkill(name)

    def pause(self, test, node):
        for name in ("stolon-keeper", "postgres"):
            cu.grepkill(name, sig="STOP")

    def resume(self, test, node):
        for name in ("stolon-keeper", "postgres"):
            cu.grepkill(name, sig="CONT")

    def log_files(self, test, node):
        return [f"{DIR}/sentinel.log", f"{DIR}/keeper.log",
                f"{DIR}/proxy.log"]


SUPPORTED_WORKLOADS = ("append", "register", "set", "bank", "ledger")


def stolon_test(opts_dict: dict | None = None) -> dict:
    from jepsen_tpu.workloads import ledger
    return build_suite_test(
        opts_dict, db_name="stolon", supported_workloads=SUPPORTED_WORKLOADS,
        extra_workloads={"ledger": ledger.workload},
        make_real=lambda o: {
            "db": StolonDB(o.get("version", DEFAULT_VERSION)),
            "client": PGSuiteClient(
                port=PROXY_PORT, database=DB_NAME, user=DB_USER,
                password=DB_PASS,
                isolation=o.get("isolation", "serializable")),
            "os": Debian()})


main_all = standard_test_all(stolon_test, SUPPORTED_WORKLOADS,
                             name="jepsen-stolon")

main = cli.single_test_cmd(
    standard_test_fn(stolon_test, extra_keys=("isolation", "version")),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: (
                        p.add_argument("--isolation", default="serializable",
                                       choices=["read-committed",
                                                "repeatable-read",
                                                "serializable"]),
                        p.add_argument("--version",
                                       default=DEFAULT_VERSION))),
    name="jepsen-stolon")


if __name__ == "__main__":
    import sys
    sys.exit(main())
