"""Disque test suite (reference: disque/src/jepsen/disque.clj — antirez's
distributed job queue, tested as a total queue under node restarts and
partitions).

Disque speaks the redis wire protocol with its own command set
(disque.clj:141-153): ``ADDJOB queue body ms-timeout REPLICATE n RETRY
s`` to enqueue, ``GETJOB TIMEOUT ms COUNT 1 FROM queue`` to claim, and
``ACKJOB id`` to acknowledge. A dequeue that times out with no job is a
definite ``fail`` (disque.clj:194-208); a ``NOREPL`` reply (job not
replicated to enough nodes before the partition) is indeterminate
(disque.clj:244-247). Cluster formation is ``CLUSTER MEET`` of every
node to the primary (disque.clj:95-105).

The workload is the shared queue kit (enqueue unique ints / dequeue /
final drain), checked with total-queue multiset algebra — exactly the
reference's ``model/unordered-queue`` + ``checker/total-queue`` pairing
(disque.clj:305-310).
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._resp import RespConnection, RespError

logger = logging.getLogger("jepsen.disque")

DEFAULT_VERSION = "f00dd0704128707f7a5effccd5837d796f2c01e3"
DIR = "/opt/disque"
DATA_DIR = "/var/lib/disque"
PIDFILE = "/var/run/disque.pid"
BINARY = f"{DIR}/src/disque-server"
LOG_FILE = f"{DATA_DIR}/log"
PORT = 7711
QUEUE = "jepsen"


class DisqueDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Build from source at a pinned commit, run via daemon helpers, join
    every node to node 1 with CLUSTER MEET (disque.clj:40-136)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        from jepsen_tpu import control
        if not cu.file_exists(BINARY):
            logger.info("%s: building disque @ %s", node, self.version)
            control.exec_("mkdir", "-p", "/opt")
            with control.cd("/opt"):
                if not cu.file_exists(DIR):
                    control.exec_("git", "clone",
                                  "https://github.com/antirez/disque.git")
            with control.cd(DIR):
                control.exec_("git", "reset", "--hard", self.version)
                control.exec_("make")
        control.exec_("mkdir", "-p", DATA_DIR)
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node)
        # CLUSTER MEET barriers on every node being up (disque.clj:99
        # jepsen/synchronize) — builds from source have minutes of variance
        from jepsen_tpu import core
        core.synchronize(test, timeout_s=600.0)  # sized for make variance
        self.join(test, node)

    def join(self, test, node):
        """CLUSTER MEET everyone to the primary (disque.clj:95-105)."""
        from jepsen_tpu import control
        from jepsen_tpu.net import resolve_ip
        nodes = test.get("nodes") or [node]
        primary = nodes[0]
        if node != primary:
            control.exec_(f"{DIR}/src/disque", "-p", str(PORT),
                          "cluster", "meet",
                          resolve_ip(test, primary), str(PORT))

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DATA_DIR)  # recreated by setup's mkdir -p

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            BINARY, "--port", str(PORT), "--bind", "0.0.0.0",
            "--appendonly", "yes", "--dir", DATA_DIR)

    def kill(self, test, node):
        cu.stop_daemon("disque-server", PIDFILE)
        cu.grepkill("disque-server")

    def pause(self, test, node):
        cu.grepkill("disque-server", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("disque-server", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class _AckLost(Exception):
    """GETJOB delivered a job but the ACKJOB reply was lost; carries the
    job body so the completion can report what may have been dequeued."""

    def __init__(self, body: int):
        super().__init__(body)
        self.body = body


class DisqueClient(Client):
    """enqueue/dequeue/drain over ADDJOB/GETJOB/ACKJOB
    (disque.clj:194-249). REPLICATE 3 / RETRY 1 job params match the
    reference client (disque.clj:254-261)."""

    def __init__(self, timeout_ms: int = 100, replicate: int = 3,
                 node: str | None = None):
        self.timeout_ms = timeout_ms
        self.replicate = replicate
        self.node = node
        self.conn: RespConnection | None = None

    def open(self, test, node):
        c = DisqueClient(self.timeout_ms, self.replicate, node)
        c.conn = RespConnection(node, PORT, timeout_s=10.0)
        return c

    def _dequeue_one(self):
        """One GETJOB+ACKJOB round; returns the job body or None.

        A network error *after* GETJOB delivered a job is re-raised as
        ``_AckLost(body)``: the ACK may or may not have applied, so the
        caller must report an indeterminate ``info`` carrying the value —
        a definite ``fail`` would make total-queue call the job lost.
        """
        jobs = self.conn.command("GETJOB", "TIMEOUT", self.timeout_ms,
                                 "COUNT", 1, "FROM", QUEUE)
        if not jobs:
            return None
        _queue, job_id, body = jobs[0][:3]
        try:
            self.conn.command("ACKJOB", job_id)
        except (TimeoutError, ConnectionError, OSError) as e:
            raise _AckLost(int(body)) from e
        return int(body)

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "enqueue":
                self.conn.command("ADDJOB", QUEUE, str(v), self.timeout_ms,
                                  "REPLICATE", self.replicate, "RETRY", 1)
                return {**op, "type": "ok"}
            if f == "dequeue":
                body = self._dequeue_one()
                if body is None:
                    return {**op, "type": "fail"}  # nothing to dequeue
                return {**op, "type": "ok", "value": body}
            if f == "drain":
                drained: list = []
                try:
                    while True:
                        body = self._dequeue_one()
                        if body is None:
                            return {**op, "type": "ok", "value": drained}
                        drained.append(body)
                except _AckLost as e:
                    drained.append(e.body)
                    return {**op, "type": "info", "value": drained,
                            "error": ["ack-lost"]}
                except (RespError, TimeoutError, ConnectionError,
                        OSError) as e:
                    # partial drain: these elements were definitely
                    # consumed (expand_queue_drain_ops handles info+list);
                    # dropping them would yield false 'lost' verdicts
                    return {**op, "type": "info", "value": drained,
                            "error": ["net", str(e)]}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except _AckLost as e:
            # GETJOB delivered the body, so the dequeue itself happened;
            # a lost ACK only risks redelivery (duplicated, not lost)
            return {**op, "type": "ok", "value": e.body,
                    "error": ["ack-lost"]}
        except RespError as e:
            msg = str(e)
            if msg.startswith("NOREPL"):
                # job not replicated widely enough — indeterminate
                # (disque.clj:244-247)
                return {**op, "type": "info",
                        "error": ["not-fully-replicated"]}
            return {**op, "type": "fail", "error": ["resp", msg]}
        except (TimeoutError, ConnectionError, OSError) as e:
            # dequeue: the error preceded any delivery (post-delivery
            # errors surface as _AckLost above), so nothing was consumed
            kind = "fail" if f == "dequeue" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


SUPPORTED_WORKLOADS = ("queue",)


def disque_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="disque", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": DisqueDB(o.get("version",
                                                  DEFAULT_VERSION)),
                             "client": DisqueClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(disque_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-disque")


if __name__ == "__main__":
    import sys
    sys.exit(main())
