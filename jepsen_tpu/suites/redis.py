"""Redis test suite (reference: the redis/raftis/disque family of suites
in jaydenwen123/jepsen — a primary/replica redis cluster whose classic
failure mode is lost updates across partitions).

The client speaks RESP (the redis wire protocol) over a plain socket —
no driver dependency — with a tiny protocol core: arrays of bulk
strings out, the five reply types in. Registers are per-key strings;
compare-and-set runs server-side as an atomic Lua EVAL (GET == old →
SET new), so a lost race is a definite ``fail``. Set adds are SADD into
one redis set, whole-set reads SMEMBERS.

DB automation installs a redis release tarball (built from source the
first time, cached thereafter), starts node 1 as the primary and the
rest as replicas (``--replicaof n1``), and directs all writes at the
primary — the topology whose partition behavior the original Jepsen
redis analyses demonstrated.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)

logger = logging.getLogger("jepsen.redis")

DEFAULT_VERSION = "7.2.5"
DIR = "/opt/redis"
LOG_FILE = f"{DIR}/redis.log"
PIDFILE = f"{DIR}/redis.pid"
PORT = 6379

CAS_LUA = ("if redis.call('GET', KEYS[1]) == ARGV[1] then "
           "redis.call('SET', KEYS[1], ARGV[2]) return 1 "
           "else return 0 end")


def archive_url(version: str) -> str:
    return f"https://download.redis.io/releases/redis-{version}.tar.gz"


class RedisDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.Primary,
              db_mod.LogFiles):
    """Primary/replica redis lifecycle; node 1 is the primary."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        from jepsen_tpu import control
        # install_archive wipes its destination, which would delete the
        # compiled binary — skip the whole unpack+build when it exists so
        # the from-source build really does happen only once per node
        if not cu.file_exists(f"{DIR}/src/redis-server"):
            logger.info("%s: installing redis %s", node, self.version)
            cu.install_archive(archive_url(self.version), DIR)
            with control.cd(DIR):
                control.exec_("make", "-j4")
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/dump.rdb")
        cu.rm_rf(LOG_FILE)

    def start(self, test, node):
        primary = (test.get("nodes") or [node])[0]
        args = ["--port", str(PORT), "--bind", "0.0.0.0",
                "--protected-mode", "no", "--appendonly", "no",
                "--save", ""]
        if node != primary:
            args += ["--replicaof", primary, str(PORT)]
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/src/redis-server", *args)

    def kill(self, test, node):
        cu.stop_daemon("redis-server", PIDFILE)
        cu.grepkill("redis-server")

    def pause(self, test, node):
        cu.grepkill("redis-server", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("redis-server", sig="CONT")

    def primaries(self, test):
        return (test.get("nodes") or [])[:1]

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        return [LOG_FILE]


# RESP protocol core shared with the raftis/disque suites
from jepsen_tpu.suites._resp import RespConnection, RespError  # noqa: E402,F401


class RedisClient(Client):
    """r/w/cas registers + set ops over RESP, always against the primary
    (node 1) — replicas are read-only and redis offers no quorum reads."""

    def __init__(self, prefix: str = "jepsen", timeout_s: float = 5.0,
                 node: str | None = None):
        self.prefix = prefix
        self.timeout_s = timeout_s
        self.node = node
        self.conn: RespConnection | None = None

    def open(self, test, node):
        primary = (test.get("nodes") or [node])[0]
        c = RedisClient(self.prefix, self.timeout_s, node)
        c.conn = RespConnection(primary, PORT, timeout_s=self.timeout_s)
        return c

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "add":
                self.conn.command("SADD", f"{self.prefix}-set", v)
                return {**op, "type": "ok"}
            if f == "read" and v is None:  # whole-set read
                members = self.conn.command("SMEMBERS", f"{self.prefix}-set")
                return {**op, "type": "ok",
                        "value": sorted(int(m) for m in (members or []))}
            if f == "read":
                k, _ = v
                raw = self.conn.command("GET", f"{self.prefix}:{k}")
                return {**op, "type": "ok",
                        "value": [k, int(raw) if raw is not None else None]}
            if f == "write":
                k, val = v
                self.conn.command("SET", f"{self.prefix}:{k}", val)
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                applied = self.conn.command(
                    "EVAL", CAS_LUA, 1, f"{self.prefix}:{k}", old, new)
                return {**op, "type": "ok" if applied == 1 else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except RespError as e:
            # a definite server-side rejection (e.g. READONLY after a
            # failover demotes our primary) — the op did not apply
            return {**op, "type": "fail", "error": ["resp", str(e)]}
        except (TimeoutError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


SUPPORTED_WORKLOADS = ("register", "set")


def redis_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="redis", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": RedisDB(o.get("version", DEFAULT_VERSION)),
                             "client": RedisClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(redis_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-redis")


if __name__ == "__main__":
    import sys
    sys.exit(main())
