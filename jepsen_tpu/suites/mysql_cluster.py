"""MySQL Cluster (NDB) test suite (reference:
mysql-cluster/src/jepsen/mysql_cluster.clj — the three-daemon NDB
topology: management servers, storage nodes, and SQL front ends).

The reference suite builds the topology but ships only a noop test map
(mysql_cluster.clj:220-227 ``simple-test``); here the shared MySQL-wire
client additionally runs register/set/bank against the SQL nodes with
``ENGINE=NDBCLUSTER`` tables, which is the natural workload surface for
the same deployment.

Topology per mysql_cluster.clj:54-118: every node gets a management
daemon (node ids 1..n), the first four get storage daemons (ids 11..),
and every node gets a mysqld (ids 21..) whose ndb connect string lists
all nodes. Startup order is mgmd → barrier → ndbd → barrier → mysqld
(mysql_cluster.clj:188-203).
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._mysql_client import (MySQLSuiteClient,
                                             create_db_and_user)

logger = logging.getLogger("jepsen.mysql_cluster")

PORT = 3306
DB_NAME = "jepsen"
DB_USER = "jepsen"
DB_PASS = "jepsen"
MGMD_DIR = "/var/lib/mysql/cluster"
NDBD_DIR = "/var/lib/mysql/data"
MYSQLD_DIR = "/var/lib/mysql/mysql"
CONFIG_INI = "/etc/my.config.ini"
MY_CNF = "/etc/my.cnf"
# node-id blocks per role (mysql_cluster.clj:54-75)
MGMD_ID_OFFSET = 1
NDBD_ID_OFFSET = 11
MYSQLD_ID_OFFSET = 21
NDBD_COUNT = 4


def node_index(test: dict, node: str) -> int:
    return (test.get("nodes") or [node]).index(node)


def ndbd_nodes(test: dict) -> list[str]:
    """First four nodes carry storage daemons (mysql_cluster.clj:99-103)."""
    return sorted((test.get("nodes") or [])[:NDBD_COUNT])


def config_ini(test: dict) -> str:
    """The cluster-wide config.ini role snippets
    (mysql_cluster.clj:77-118)."""
    nodes = test.get("nodes") or []
    parts = ["[ndbd default]", "NoOfReplicas=2", ""]
    for n in nodes:
        parts += [f"[ndb_mgmd]",
                  f"NodeId={MGMD_ID_OFFSET + node_index(test, n)}",
                  f"hostname={n}", f"datadir={MGMD_DIR}", ""]
    for n in ndbd_nodes(test):
        parts += [f"[ndbd]",
                  f"NodeId={NDBD_ID_OFFSET + node_index(test, n)}",
                  f"hostname={n}", f"datadir={NDBD_DIR}", ""]
    for n in nodes:
        parts += [f"[mysqld]",
                  f"NodeId={MYSQLD_ID_OFFSET + node_index(test, n)}",
                  f"hostname={n}", ""]
    return "\n".join(parts)


def ndb_connect_string(test: dict) -> str:
    return ",".join(test.get("nodes") or [])


def my_cnf(test: dict, node: str) -> str:
    """The per-node mysqld config (mysql_cluster.clj:120-132)."""
    return "\n".join([
        "[mysqld]",
        "ndbcluster",
        f"ndb-nodeid={MYSQLD_ID_OFFSET + node_index(test, node)}",
        f"ndb-connectstring={ndb_connect_string(test)}",
        f"datadir={MYSQLD_DIR}",
        "bind-address=0.0.0.0",
        "user=mysql",
        "",
        "[mysql_cluster]",
        f"ndb-connectstring={ndb_connect_string(test)}",
        "",
    ])


class MySQLClusterDB(db_mod.DB, db_mod.Process, db_mod.LogFiles):
    """NDB lifecycle (mysql_cluster.clj:140-218): mgmd everywhere,
    ndbd on the first four nodes, mysqld everywhere, phase barriers
    between the three role startups."""

    def __init__(self, package: str = "mysql-cluster-community-server"):
        self.package = package

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing mysql cluster", node)
        os_setup.install(["libaio1", "libncurses5"])
        os_setup.install([self.package])
        for d in (MGMD_DIR, NDBD_DIR, MYSQLD_DIR):
            cu.mkdir(d)
        cu.write_file(config_ini(test), CONFIG_INI)
        cu.write_file(my_cnf(test, node), MY_CNF)
        self.start_mgmd(test, node)
        core.synchronize(test, timeout_s=300.0)
        self.start_ndbd(test, node)
        core.synchronize(test, timeout_s=300.0)
        self.start_mysqld(test, node)
        cu.await_tcp_port(PORT, host=node, timeout_s=120.0)
        create_db_and_user(DB_NAME, DB_USER, DB_PASS)

    def start_mgmd(self, test, node):
        """Management daemon (mysql_cluster.clj:140-147)."""
        control.exec_("ndb_mgmd",
                      f"--ndb-nodeid={MGMD_ID_OFFSET + node_index(test, node)}",
                      "-f", CONFIG_INI,
                      "--configdir=" + MGMD_DIR)

    def start_ndbd(self, test, node):
        """Storage daemon on the first four nodes only
        (mysql_cluster.clj:149-157)."""
        if node in ndbd_nodes(test):
            control.exec_(
                "ndbd",
                f"--ndb-nodeid={NDBD_ID_OFFSET + node_index(test, node)}")

    def start_mysqld(self, test, node):
        """SQL daemon (mysql_cluster.clj:159-167). An empty datadir is
        initialized first — the package postinst only initializes the
        default location, not our my.cnf's."""
        if not cu.file_exists(f"{MYSQLD_DIR}/mysql"):
            control.exec_(control.lit(
                f"mysqld --defaults-file={MY_CNF} --initialize-insecure "
                f">/dev/null 2>&1 || true"))
        return cu.start_daemon(
            {"logfile": f"{MYSQLD_DIR}/mysqld.log",
             "pidfile": f"{MYSQLD_DIR}/mysqld.pid",
             "chdir": MYSQLD_DIR},
            "mysqld", f"--defaults-file={MY_CNF}")

    def teardown(self, test, node):
        for proc in ("mysqld", "ndbd", "ndb_mgmd"):
            cu.grepkill(proc)
        for d in (MGMD_DIR, NDBD_DIR, MYSQLD_DIR):
            cu.rm_rf(d)

    def start(self, test, node):
        self.start_mysqld(test, node)

    def kill(self, test, node):
        cu.grepkill("mysqld")

    def log_files(self, test, node):
        return [f"{MYSQLD_DIR}/mysqld.log"]


SUPPORTED_WORKLOADS = ("register", "set", "bank")


def mysql_cluster_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="mysql-cluster",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": MySQLClusterDB(),
            "client": MySQLSuiteClient(
                port=PORT, database=DB_NAME, user=DB_USER, password=DB_PASS,
                isolation=o.get("isolation", "repeatable-read"),
                engine="NDBCLUSTER"),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(mysql_cluster_test, extra_keys=("isolation",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--isolation", default="repeatable-read",
                        choices=["read-committed", "repeatable-read",
                                 "serializable"])),
    name="jepsen-mysql-cluster")


if __name__ == "__main__":
    import sys
    sys.exit(main())
