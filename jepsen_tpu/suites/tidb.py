"""TiDB test suite (reference: tidb/src/tidb/ — PD placement drivers,
TiKV storage, and MySQL-protocol tidb-servers; the reference's richest
SQL suite, with register/set/bank/txn workloads, tidb/src/tidb/core.clj
workloads-as-data).

Workloads ride the shared MySQL-wire client on port 4000:
``register``/``set``/``bank`` (tidb/src/tidb/{register,sets,bank}.clj)
plus the Elle ``append`` and ``wr`` transactional workloads whose
micro-op SQL mirrors tidb/src/tidb/txn.clj:19-48 (CONCAT-upsert
appends).

DB automation mirrors tidb/src/tidb/db.clj: one release tarball, then
per node pd-server (client 2379 / peer 2380, full --initial-cluster),
tikv-server (20160, --pd endpoints), and tidb-server (--store tikv,
port 4000), with barriers between the three tiers and health waits.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)
from jepsen_tpu.suites._mysql_client import MySQLSuiteClient

logger = logging.getLogger("jepsen.tidb")

DEFAULT_VERSION = "v7.1.5"
DIR = "/opt/tidb"
BIN = f"{DIR}/bin"
PD_CLIENT_PORT = 2379
PD_PEER_PORT = 2380
KV_PORT = 20160
SQL_PORT = 4000
DB_NAME = "jepsen"
# the root user ships passwordless (tidb/src/tidb/sql.clj conn specs)
DB_USER = "root"
DB_PASS = ""

PD_LOG = f"{DIR}/pd.log"
KV_LOG = f"{DIR}/kv.log"
DB_LOG = f"{DIR}/db.log"


def tarball_url(version: str) -> str:
    return (f"https://download.pingcap.org/tidb-community-server-"
            f"{version}-linux-amd64.tar.gz")


def pd_name(test: dict, node: str) -> str:
    """pd1..pdn (tidb/db.clj:48-55 tidb-map)."""
    return f"pd{(test.get('nodes') or [node]).index(node) + 1}"


def initial_cluster(test: dict) -> str:
    """``pd1=http://n1:2380,...`` (tidb/db.clj:72-78)."""
    return ",".join(f"{pd_name(test, n)}=http://{n}:{PD_PEER_PORT}"
                    for n in (test.get("nodes") or []))


def pd_endpoints(test: dict) -> str:
    """``n1:2379,n2:2379,...`` (tidb/db.clj:80-87)."""
    return ",".join(f"{n}:{PD_CLIENT_PORT}"
                    for n in (test.get("nodes") or []))


class TiDBDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Three-tier lifecycle with per-tier barriers
    (tidb/db.clj:165-215,287-310)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        from jepsen_tpu import core
        if not cu.file_exists(f"{BIN}/pd-server"):
            logger.info("%s: installing tidb %s", node, self.version)
            cu.install_archive(tarball_url(self.version), DIR)
            cu.mkdir(BIN)
            # the community tarball nests binaries one directory down
            control.exec_(control.lit(
                f"find {DIR} -name pd-server -o -name tikv-server "
                f"-o -name tidb-server | xargs -I{{}} cp {{}} {BIN}/"))
        self.start_pd(test, node)
        cu.await_tcp_port(PD_CLIENT_PORT, host=node, timeout_s=120.0)
        core.synchronize(test, timeout_s=600.0)
        self.start_kv(test, node)
        cu.await_tcp_port(KV_PORT, host=node, timeout_s=120.0)
        core.synchronize(test, timeout_s=600.0)
        self.start_db(test, node)
        cu.await_tcp_port(SQL_PORT, host=node, timeout_s=180.0)
        control.exec_(control.lit(
            f"mysql -h 127.0.0.1 -P {SQL_PORT} -u root -e "
            f"'CREATE DATABASE IF NOT EXISTS {DB_NAME}' "
            f"2>/dev/null || true"))

    def start_pd(self, test, node):
        """pd-server argv (tidb/db.clj:165-183)."""
        return cu.start_daemon(
            {"logfile": f"{DIR}/pd.stdout", "pidfile": f"{DIR}/pd.pid",
             "chdir": DIR},
            f"{BIN}/pd-server",
            "--name", pd_name(test, node),
            "--data-dir", f"{DIR}/data/pd",
            "--client-urls", f"http://0.0.0.0:{PD_CLIENT_PORT}",
            "--peer-urls", f"http://0.0.0.0:{PD_PEER_PORT}",
            "--advertise-client-urls", f"http://{node}:{PD_CLIENT_PORT}",
            "--advertise-peer-urls", f"http://{node}:{PD_PEER_PORT}",
            "--initial-cluster", initial_cluster(test),
            "--log-file", PD_LOG)

    def start_kv(self, test, node):
        """tikv-server argv (tidb/db.clj:185-200)."""
        return cu.start_daemon(
            {"logfile": f"{DIR}/kv.stdout", "pidfile": f"{DIR}/kv.pid",
             "chdir": DIR},
            f"{BIN}/tikv-server",
            "--pd", pd_endpoints(test),
            "--addr", f"0.0.0.0:{KV_PORT}",
            "--advertise-addr", f"{node}:{KV_PORT}",
            "--data-dir", f"{DIR}/data/kv",
            "--log-file", KV_LOG)

    def start_db(self, test, node):
        """tidb-server argv (tidb/db.clj:202-215)."""
        return cu.start_daemon(
            {"logfile": f"{DIR}/db.stdout", "pidfile": f"{DIR}/db.pid",
             "chdir": DIR},
            f"{BIN}/tidb-server",
            "-P", str(SQL_PORT),
            "--store", "tikv",
            "--path", pd_endpoints(test),
            "--log-file", DB_LOG)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/data")
        for f in (PD_LOG, KV_LOG, DB_LOG):
            cu.rm_rf(f)

    def start(self, test, node):
        self.start_pd(test, node)
        self.start_kv(test, node)
        self.start_db(test, node)

    def kill(self, test, node):
        for proc in ("tidb-server", "tikv-server", "pd-server"):
            cu.grepkill(proc)

    def pause(self, test, node):
        for proc in ("tidb-server", "tikv-server", "pd-server"):
            cu.grepkill(proc, sig="STOP")

    def resume(self, test, node):
        for proc in ("tidb-server", "tikv-server", "pd-server"):
            cu.grepkill(proc, sig="CONT")

    def log_files(self, test, node):
        return [PD_LOG, KV_LOG, DB_LOG]


SUPPORTED_WORKLOADS = ("append", "register", "set", "bank", "wr", "table",
                       "long-fork", "set-cas", "bank-multitable",
                       "monotonic", "sequential")


def _tidb_workload(name: str, base: dict) -> dict:
    """The shared kits plus tidb's registry variants
    (tidb/core.clj:32-45): set-cas re-runs the set workload through the
    single-text-row CAS client (tidb/sets.clj CasSetClient) and
    bank-multitable re-runs bank across per-account tables
    (tidb/bank.clj MultiBankClient) — kit semantics unchanged, a
    test-map marker routes the client. ``monotonic`` is tidb's OWN
    monotonic probe (tidb/monotonic.clj inc-workload: per-key
    increments + pool reads under a monotonic-key+realtime cycle
    check), not the cockroach timestamp workload; ``sequential`` is the
    shared kit over per-hash tables (tidb/sequential.clj)."""
    from jepsen_tpu.suites import workload_registry

    reg = workload_registry()
    if name == "set-cas":
        return {**reg["set"](base, accelerator=base["accelerator"]),
                "set-cas": True}
    if name == "bank-multitable":
        return {**reg["bank"](base, accelerator=base["accelerator"]),
                "bank-multitable": True}
    if name == "monotonic":
        from jepsen_tpu.workloads import monotonic_key
        return monotonic_key.workload(base)
    return reg[name](base, accelerator=base["accelerator"])


def tidb_test(opts_dict: dict | None = None) -> dict:
    o = dict(opts_dict or {})
    workload = o.get("workload") or SUPPORTED_WORKLOADS[0]
    return build_suite_test(
        o, db_name="tidb", supported_workloads=SUPPORTED_WORKLOADS,
        make_workload=_tidb_workload,
        make_real=lambda o: {
            "db": TiDBDB(o.get("version", DEFAULT_VERSION)),
            "client": MySQLSuiteClient(
                port=SQL_PORT, database=DB_NAME, user=DB_USER,
                password=DB_PASS,
                isolation=o.get("isolation", "repeatable-read"),
                txn_style="wr" if workload in ("wr", "long-fork")
                else "append"),
            "os": Debian()})


main_all = standard_test_all(tidb_test, SUPPORTED_WORKLOADS,
                             name="jepsen-tidb")

main = cli.single_test_cmd(
    standard_test_fn(tidb_test, extra_keys=("isolation", "version")),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: (
                        p.add_argument("--isolation",
                                       default="repeatable-read",
                                       choices=["read-committed",
                                                "repeatable-read",
                                                "serializable"]),
                        p.add_argument("--version",
                                       default=DEFAULT_VERSION))),
    name="jepsen-tidb")


if __name__ == "__main__":
    import sys
    sys.exit(main())
