"""CrateDB test suite (reference: crate/ in jaydenwen123/jepsen —
crate/src/jepsen/crate/core.clj plus the dirty_read / lost_updates /
version_divergence workloads probing Crate's eventually-durable SQL
over Elasticsearch).

The client speaks Crate's HTTP ``_sql`` endpoint (POST {stmt, args})
with stdlib urllib. Register CAS is an optimistic
``UPDATE ... WHERE id=? AND val=?`` judged by rowcount — the
lost-updates shape; set adds INSERT one row per element and final reads
``REFRESH TABLE`` first (Crate reads are refresh-bounded, the
version_divergence lesson). DB automation installs the tarball, writes
unicast discovery over the node list, and runs bin/crate.
"""
from __future__ import annotations

import logging
import urllib.error

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_json

logger = logging.getLogger("jepsen.crate")

DEFAULT_VERSION = "5.7.2"
DIR = "/opt/crate"
LOG_FILE = f"{DIR}/logs/jepsen.log"
PIDFILE = f"{DIR}/crate.pid"
PORT = 4200


def archive_url(version: str) -> str:
    return (f"https://cdn.crate.io/downloads/releases/cratedb/x64_linux/"
            f"crate-{version}.tar.gz")


class CrateDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION,
                 es_api: bool = False):
        self.version = version
        self.es_api = es_api  # expose the embedded ES HTTP API

    def setup(self, test, node):
        logger.info("%s: installing crate %s", node, self.version)
        cu.install_archive(archive_url(self.version), DIR)
        nodes = test.get("nodes") or []
        conf = "\n".join([
            "cluster.name: jepsen",
            f"node.name: {node}",
            "network.host: 0.0.0.0",
            f"discovery.seed_hosts: [{', '.join(nodes)}]",
            f"cluster.initial_master_nodes: [{', '.join(nodes)}]",
            f"gateway.expected_data_nodes: {len(nodes)}",
            f"gateway.recover_after_data_nodes: {max(1, len(nodes) // 2 + 1)}",
        ] + (
            # --es-ops routing needs the embedded ES HTTP API (only
            # crate versions that still carry it honor this setting)
            ["es.api.enabled: true"] if self.es_api else []
        )) + "\n"
        from jepsen_tpu import control
        control.exec_("tee", f"{DIR}/config/crate.yml", stdin=conf)
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/data")

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/bin/crate")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/bin/crate", PIDFILE)
        cu.grepkill("io.crate.bootstrap.CrateDB")

    def pause(self, test, node):
        cu.grepkill("io.crate.bootstrap.CrateDB", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("io.crate.bootstrap.CrateDB", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class CrateClient(Client):
    """SQL over the HTTP ``_sql`` endpoint.

    ``es_ops`` routes a subset of the dirty-read probe's op ``f``s
    through Crate's embedded Elasticsearch HTTP API instead of SQL
    (dirty_read.clj:97-141 es-client — requires a crate version that
    still exposes the ES API; setup adds ``es.api.enabled`` when the
    routing is requested)."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None,
                 es_ops: frozenset = frozenset()):
        self.timeout_s = timeout_s
        self.node = node
        self.es_ops = frozenset(es_ops or ())

    def open(self, test, node):
        return CrateClient(self.timeout_s, node, self.es_ops)

    def _sql(self, stmt: str, args: list | None = None):
        return http_json(f"http://{self.node}:{PORT}/_sql",
                         {"stmt": stmt, "args": args or []},
                         timeout_s=self.timeout_s)

    def setup(self, test):
        self._sql("CREATE TABLE IF NOT EXISTS registers "
                  "(id INT PRIMARY KEY, val INT) "
                  "CLUSTERED INTO 5 SHARDS WITH (number_of_replicas = 2)")
        self._sql("CREATE TABLE IF NOT EXISTS sets "
                  "(id INT PRIMARY KEY) "
                  "CLUSTERED INTO 5 SHARDS WITH (number_of_replicas = 2)")
        self._sql("CREATE TABLE IF NOT EXISTS lu "
                  "(id INT PRIMARY KEY, elements ARRAY(INT)) "
                  "CLUSTERED INTO 5 SHARDS WITH (number_of_replicas = 2)")
        # dirty_read.clj:43-50: replicate everywhere so every node's
        # strong read scans a local copy
        self._sql("CREATE TABLE IF NOT EXISTS dirty_read "
                  "(id INT PRIMARY KEY) "
                  "WITH (number_of_replicas = '0-all')")

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if test.get("dirty-read"):
                return self._dirty_read_op(op, f, v)
            if test.get("version-divergence") and f == "read":
                k, _ = v
                res = self._sql(
                    "SELECT val, _version FROM registers WHERE id = ?",
                    [int(k)])
                rows = res.get("rows") or []
                pair = ([rows[0][0], rows[0][1]] if rows
                        else [None, None])
                return {**op, "type": "ok", "value": [k, pair]}
            if test.get("version-divergence") and f == "write":
                k, val = v
                # blind upsert: the store advances _version per write
                # (version_divergence.clj's on-duplicate-key insert)
                self._sql(
                    "INSERT INTO registers (id, val) VALUES (?, ?) "
                    "ON CONFLICT (id) DO UPDATE SET val = excluded.val",
                    [int(k), int(val)])
                return {**op, "type": "ok"}
            if test.get("lost-updates") and f == "add":
                return self._lu_add(op)
            if test.get("lost-updates") and f == "read":
                k, _ = v
                self._sql("REFRESH TABLE lu")
                res = self._sql("SELECT elements FROM lu WHERE id = ?",
                                [int(k)])
                rows = res.get("rows") or []
                els = sorted(rows[0][0]) if rows and rows[0][0] else []
                return {**op, "type": "ok", "value": [k, els]}
            if f == "add":
                self._sql("INSERT INTO sets (id) VALUES (?)", [v])
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                self._sql("REFRESH TABLE sets")
                res = self._sql("SELECT id FROM sets ORDER BY id")
                return {**op, "type": "ok",
                        "value": [row[0] for row in res["rows"]]}
            if f == "read":
                k, _ = v
                self._sql("REFRESH TABLE registers")
                res = self._sql("SELECT val FROM registers WHERE id = ?", [k])
                val = res["rows"][0][0] if res["rows"] else None
                return {**op, "type": "ok", "value": [k, val]}
            if f == "write":
                k, val = v
                res = self._sql("UPDATE registers SET val = ? WHERE id = ?",
                                [val, k])
                if res.get("rowcount", 0) == 0:
                    self._sql("INSERT INTO registers (id, val) VALUES (?, ?)",
                              [k, val])
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                self._sql("REFRESH TABLE registers")
                res = self._sql(
                    "UPDATE registers SET val = ? WHERE id = ? AND val = ?",
                    [new, k, old])
                ok = res.get("rowcount", 0) == 1
                return {**op, "type": "ok" if ok else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            if e.code == 409:  # duplicate key / version conflict
                return {**op, "type": "fail", "error": ["conflict", e.code]}
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def _dirty_read_op(self, op, f, v):
        """The crate dirty-read probe's op surface
        (dirty_read.clj:54-141): point read by id, unique-int insert,
        table refresh, and the full strong-read scan — each routable
        through the ES API instead of SQL via ``es_ops``."""
        if f in self.es_ops:
            base = f"http://{self.node}:{PORT}"
            if f == "write":
                http_json(f"{base}/dirty_read/default/{int(v)}",
                          {"id": int(v)}, method="PUT",
                          timeout_s=self.timeout_s)
                return {**op, "type": "ok"}
            if f == "read":
                try:
                    doc = http_json(f"{base}/dirty_read/default/{int(v)}",
                                    timeout_s=self.timeout_s)
                except urllib.error.HTTPError as e:
                    if e.code == 404:
                        return {**op, "type": "fail"}
                    raise
                found = bool((doc or {}).get("found"))
                return {**op, "type": "ok" if found else "fail"}
            if f == "strong-read":
                # search_after pages (the elasticsearch suite's paging
                # pattern): a single giant-size request trips ES's
                # max_result_window cap on exactly the versions under
                # probe, turning every strong read into info
                ids: list = []
                after = None
                while True:
                    body = {"size": 10000, "_source": ["id"],
                            "query": {"match_all": {}},
                            "sort": [{"id": "asc"}]}
                    if after is not None:
                        body["search_after"] = after
                    res = http_json(f"{base}/dirty_read/_search", body,
                                    timeout_s=self.timeout_s)
                    hits = ((res or {}).get("hits") or {}).get("hits") or []
                    ids.extend(int(h["_source"]["id"]) for h in hits)
                    if len(hits) < 10000:
                        break
                    after = hits[-1]["sort"]
                return {**op, "type": "ok", "value": sorted(ids)}
            # refresh falls through to SQL either way
        if f == "write":
            self._sql("INSERT INTO dirty_read (id) VALUES (?)", [int(v)])
            return {**op, "type": "ok"}
        if f == "read":
            res = self._sql("SELECT id FROM dirty_read WHERE id = ?",
                            [int(v)])
            found = bool(res.get("rows"))
            return {**op, "type": "ok" if found else "fail"}
        if f == "refresh":
            self._sql("REFRESH TABLE dirty_read")
            return {**op, "type": "ok"}
        if f == "strong-read":
            res = self._sql("SELECT id FROM dirty_read LIMIT 100000000")
            return {**op, "type": "ok",
                    "value": sorted(r[0] for r in res.get("rows") or [])}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    def _lu_add(self, op):
        """Read-modify-write under crate's optimistic _version guard
        (lost_updates.clj): append the element to the key's list only if
        the row hasn't changed since the read; retry conflicts, and fail
        definitively when retries exhaust — a lost ACKED add is the
        anomaly, so an unacked add must never linger as ok."""
        k, el = op.get("value")
        k, el = int(k), int(el)
        ambiguous = False
        for _ in range(5):
            self._sql("REFRESH TABLE lu")
            res = self._sql(
                "SELECT elements, _version FROM lu WHERE id = ?", [k])
            rows = res.get("rows") or []
            if not rows:
                try:
                    ins = self._sql(
                        "INSERT INTO lu (id, elements) VALUES (?, ?)",
                        [k, [el]])
                    if ins.get("rowcount", 0) == 1:
                        return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    # 409 = raced another first insert (definitely not
                    # ours); anything else may have applied server-side
                    if e.code != 409:
                        ambiguous = True
                continue
            elements, version = rows[0]
            upd = self._sql(
                "UPDATE lu SET elements = ? WHERE id = ? AND _version = ?",
                [list(elements or []) + [el], k, int(version)])
            if upd.get("rowcount", 0) == 1:
                return {**op, "type": "ok"}
        if ambiguous:
            # an insert attempt may have landed: the op is indeterminate,
            # a definite fail here would turn a surviving element into a
            # false anomaly under fail-semantics checkers
            return {**op, "type": "info", "error": ["ambiguous-insert", k, el]}
        return {**op, "type": "fail", "error": ["version-conflict", k, el]}

    def close(self, test):
        pass


SUPPORTED_WORKLOADS = ("register", "set", "lost-updates",
                       "version-divergence", "dirty-read")


def _parse_es_ops(raw) -> frozenset:
    """``--es-ops read,write`` → the op fs routed through the ES API
    (dirty_read.clj:228-241's :es-ops set)."""
    if not raw:
        return frozenset()
    if isinstance(raw, (set, frozenset, list, tuple)):
        return frozenset(raw)
    return frozenset(s.strip() for s in str(raw).split(",") if s.strip())


def crate_test(opts_dict: dict | None = None) -> dict:
    from jepsen_tpu.workloads import crate_dirty_read

    o = dict(opts_dict or {})
    es_ops = _parse_es_ops(o.get("es_ops"))
    return build_suite_test(
        o, db_name="crate", supported_workloads=SUPPORTED_WORKLOADS,
        extra_workloads={
            "dirty-read": lambda base: crate_dirty_read.workload(
                base,
                quiesce_s=float(o.get("dirty_read_quiesce", 10.0)))},
        make_real=lambda o: {"db": CrateDB(o.get("version", DEFAULT_VERSION),
                                           es_api=bool(es_ops)),
                             "client": CrateClient(es_ops=es_ops),
                             "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(crate_test, extra_keys=("version", "es_ops")),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: (
                        p.add_argument("--version",
                                       default=DEFAULT_VERSION),
                        p.add_argument("--es-ops", default="",
                                       help="ops routed through the ES "
                                            "API: e.g. read,write"))),
    name="jepsen-crate")


if __name__ == "__main__":
    import sys
    sys.exit(main())
