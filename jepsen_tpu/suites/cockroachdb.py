"""CockroachDB test suite (reference: cockroachdb/src/jepsen/cockroach/
— the richest SQL suite in the reference: register, bank, sets,
monotonic (HLC-timestamp ordering), sequential, and G2 anti-dependency
workloads against a geo-replicated serializable SQL store).

Workloads ride the shared Postgres-wire client (``_pg_client.py``) on
port 26257 with ``root``/insecure auth (cockroach/auto.clj:29-54); the
monotonic workload's timestamp expression is cockroach's own
``cluster_logical_timestamp()`` HLC (cockroach/monotonic.clj:32-66),
which the checker compares as exact Decimals. ``adya`` maps the
reference's g2 predicate-anti-dependency test (cockroach/adya-ish
comments.clj/g2) onto the shared adya workload kit.

DB automation per cockroach/auto.clj: one release tarball, then
``cockroach start --insecure --store=... --join=n1,n2,...`` on every
node, a ``cockroach init`` through node 1, and the jepsen database.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)
from jepsen_tpu.suites._pg_client import PGSuiteClient

logger = logging.getLogger("jepsen.cockroachdb")

DEFAULT_VERSION = "v23.1.14"
DIR = "/opt/cockroach"
BINARY = f"{DIR}/cockroach"
STORE = f"{DIR}/cockroach-data"
LOG_DIR = f"{DIR}/logs"
PIDFILE = f"{DIR}/cockroach.pid"
SQL_PORT = 26257
HTTP_PORT = 8080
DB_NAME = "jepsen"


def tarball_url(version: str) -> str:
    return (f"https://binaries.cockroachdb.com/cockroach-"
            f"{version}.linux-amd64.tgz")


def join_spec(test: dict) -> str:
    return ",".join(f"{n}:{SQL_PORT}" for n in (test.get("nodes") or []))


class CockroachDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.Primary,
                  db_mod.LogFiles):
    """Cockroach lifecycle (cockroach/auto.clj): tarball install, start
    with --join on every node, one-shot ``init`` via node 1."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        from jepsen_tpu import core
        if not cu.file_exists(BINARY):
            logger.info("%s: installing cockroach %s", node, self.version)
            cu.install_archive(tarball_url(self.version), DIR)
            control.exec_(control.lit(
                f"find {DIR} -name cockroach -type f "
                f"| head -1 | xargs -I{{}} cp {{}} {BINARY} "
                f"&& chmod +x {BINARY}"))
        cu.mkdir(LOG_DIR)
        self.start(test, node)
        core.synchronize(test, timeout_s=600.0)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            control.exec_(control.lit(
                f"{BINARY} init --insecure --host={node}:{SQL_PORT} "
                f"2>/dev/null || true"))  # idempotent re-init says done
            cu.await_tcp_port(SQL_PORT, host=node, timeout_s=120.0)
            control.exec_(BINARY, "sql", "--insecure",
                          f"--host={node}:{SQL_PORT}", "-e",
                          f"CREATE DATABASE IF NOT EXISTS {DB_NAME}")
        core.synchronize(test, timeout_s=600.0)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(STORE)
        cu.rm_rf(LOG_DIR)

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": f"{LOG_DIR}/cockroach.stdout", "pidfile": PIDFILE,
             "chdir": DIR},
            BINARY, "start", "--insecure",
            f"--store={STORE}",
            f"--listen-addr=0.0.0.0:{SQL_PORT}",
            f"--advertise-addr={node}:{SQL_PORT}",
            f"--http-addr=0.0.0.0:{HTTP_PORT}",
            f"--join={join_spec(test)}",
            f"--log-dir={LOG_DIR}")

    def kill(self, test, node):
        cu.stop_daemon("cockroach", PIDFILE)
        cu.grepkill("cockroach")

    def pause(self, test, node):
        cu.grepkill("cockroach", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("cockroach", sig="CONT")

    def primaries(self, test):
        # cockroach is multi-primary; every node serves SQL
        return list(test.get("nodes") or [])

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        return [f"{LOG_DIR}/cockroach.stdout"]


SUPPORTED_WORKLOADS = ("register", "bank", "set", "append", "monotonic",
                       "sequential", "adya", "long-fork", "wr", "comments")


def cockroachdb_test(opts_dict: dict | None = None) -> dict:
    from jepsen_tpu.nemesis.db_specific import cockroach_fault_packages
    o = dict(opts_dict or {})
    workload = o.get("workload") or SUPPORTED_WORKLOADS[0]
    return build_suite_test(
        o, db_name="cockroachdb", supported_workloads=SUPPORTED_WORKLOADS,
        fault_packages=cockroach_fault_packages(),
        make_real=lambda o: {
            "db": CockroachDB(o.get("version", DEFAULT_VERSION)),
            "client": PGSuiteClient(
                port=SQL_PORT, database=DB_NAME, user="root", password="",
                isolation="serializable",
                ts_expr="cluster_logical_timestamp()", logical_ts=True,
                txn_style="wr" if workload in ("wr", "long-fork")
                else "append"),
            "os": Debian()})


# the named skew family (cockroach/nemesis.clj:201-271) rides --fault
COCKROACH_FAULTS = ("skew-small", "skew-subcritical", "skew-critical",
                    "skew-big", "skew-huge", "skew-strobe", "startkill")

main_all = standard_test_all(cockroachdb_test, SUPPORTED_WORKLOADS,
                             name="jepsen-cockroachdb")

main = cli.single_test_cmd(
    standard_test_fn(cockroachdb_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION),
                    extra_faults=COCKROACH_FAULTS),
    name="jepsen-cockroachdb")


if __name__ == "__main__":
    import sys
    sys.exit(main())
