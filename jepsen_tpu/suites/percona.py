"""Percona XtraDB Cluster test suite (reference:
percona/src/jepsen/percona.clj + percona/dirty_reads.clj — galera-based
synchronous replication on Percona Server; the reference probes the
same bank-sum and dirty-read anomalies as the galera suite).

Workloads ride the shared MySQL-wire client: ``bank``
(percona.clj:243-301 serializable transfers), ``dirty-reads``
(percona/dirty_reads.clj), and ``set``. DB automation mirrors
percona.clj:34-151: add the percona apt repo, pre-seed debconf root
passwords, install the cluster package, write the wsrep config, start
node 1 with ``bootstrap-pxc``, barrier, start the rest.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._mysql_client import (MySQLSuiteClient,
                                             create_db_and_user)
from jepsen_tpu.suites.galera import wsrep_config

logger = logging.getLogger("jepsen.percona")

PORT = 3306
DB_NAME = "jepsen"
DB_USER = "jepsen"
DB_PASS = "jepsen"
ROOT_PASS = "jepsen"
PACKAGE = "percona-xtradb-cluster-57"
CONF_FILE = "/etc/mysql/conf.d/jepsen.cnf"
LOG_FILES = ["/var/log/syslog", "/var/log/mysql.log", "/var/log/mysql.err",
             "/var/log/mysqld.log"]


class PerconaDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Percona XtraDB Cluster lifecycle (percona.clj:34-151)."""

    def __init__(self, package: str = PACKAGE):
        self.package = package

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing %s", node, self.package)
        os_setup.add_repo(
            "percona", "deb http://repo.percona.com/apt bullseye main",
            keyserver="keyserver.ubuntu.com", key_id="9334A25F8507EFA5")
        # pre-seed root passwords so the install is non-interactive
        # (percona.clj:52-56)
        for sel in (f"{self.package} mysql-server/root_password "
                    f"password {ROOT_PASS}",
                    f"{self.package} mysql-server/root_password_again "
                    f"password {ROOT_PASS}"):
            os_setup.debconf_set(sel)
        os_setup.install([self.package, "rsync"])
        control.exec_(control.lit(
            "service mysql stop >/dev/null 2>&1 || true"))
        cu.mkdir("/etc/mysql/conf.d")
        # PXC bundles galera-3 under /usr/lib/galera3/
        cu.write_file(
            wsrep_config(test,
                         provider="/usr/lib/galera3/libgalera_smm.so"),
            CONF_FILE)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            # bootstrap-pxc forms the new cluster (percona.clj:127)
            control.exec_("service", "mysql", "start", "bootstrap-pxc")
        core.synchronize(test, timeout_s=300.0)
        if node != primary:
            control.exec_("service", "mysql", "start")
        core.synchronize(test, timeout_s=300.0)
        cu.await_tcp_port(PORT, host=node)
        create_db_and_user(DB_NAME, DB_USER, DB_PASS, root_pass=ROOT_PASS)

    def teardown(self, test, node):
        self.kill(test, node)
        control.exec_(control.lit(
            f"mysql -u root --password={ROOT_PASS} "
            f"-e 'DROP DATABASE IF EXISTS {DB_NAME}' "
            ">/dev/null 2>&1 || true"))

    def start(self, test, node):
        control.exec_("service", "mysql", "start")

    def kill(self, test, node):
        control.exec_(control.lit(
            "service mysql stop >/dev/null 2>&1 || true"))
        cu.grepkill("mysqld")

    def pause(self, test, node):
        cu.grepkill("mysqld", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("mysqld", sig="CONT")

    def log_files(self, test, node):
        return LOG_FILES


SUPPORTED_WORKLOADS = ("bank", "dirty-reads", "set")


def percona_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="percona",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": PerconaDB(),
            "client": MySQLSuiteClient(
                port=PORT, database=DB_NAME, user=DB_USER, password=DB_PASS,
                isolation=o.get("isolation", "serializable")),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(percona_test, extra_keys=("isolation",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--isolation", default="serializable",
                        choices=["read-committed", "repeatable-read",
                                 "serializable"])),
    name="jepsen-percona")


if __name__ == "__main__":
    import sys
    sys.exit(main())
