"""PostgreSQL test suite (reference: postgres-rds/ and the stolon/
percona/galera SQL suites in jaydenwen123/jepsen — transactional SQL
stores probed for serializability anomalies).

The flagship workload is Elle-style **list-append**: each op is one SQL
transaction of reads (``SELECT elems``) and appends
(``INSERT ... ON CONFLICT ... SET elems = elems || v``) at the chosen
isolation level; the cycle checker then hunts G0/G1/G-single/G2
anomalies in the dependency graph. Register/set workloads map to a
keyed table with UPDATE-guarded compare-and-set.

The client rides the bundled wire-protocol implementation
(``suites/_postgres.py``) — no third-party driver. ``--fake`` swaps in
the in-memory doubles — including the append workload, which the fake
store applies atomically, so the Elle checker path is exercised
end-to-end without a cluster. DB automation installs the distro
postgresql, opens it to the test network, and creates the jepsen
database.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._pg_client import PGSuiteClient

logger = logging.getLogger("jepsen.postgres")

PORT = 5432
DB_NAME = "jepsen"
DB_USER = "jepsen"
DB_PASS = "jepsenpw"
CONF_DIR = "/etc/postgresql"
LOG = "/var/log/postgresql/postgresql.log"


class PostgresDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Single-node-per-host distro postgres (postgres-rds tests managed
    instances; here each node runs its own server and clients bind to
    their node, the stolon-without-replication shape)."""

    def setup(self, test, node):
        logger.info("%s: installing postgresql", node)
        from jepsen_tpu import os_setup
        os_setup.install(["postgresql", "postgresql-client"])
        # listen beyond localhost + trust the test network (test rig only)
        control.exec_(control.lit(
            "echo \"listen_addresses = '*'\" >> "
            "$(ls -d /etc/postgresql/*/main)/conf.d/jepsen.conf 2>/dev/null "
            "|| echo \"listen_addresses = '*'\" >> "
            "$(ls -d /etc/postgresql/*/main)/postgresql.conf"))
        control.exec_(control.lit(
            "echo 'host all all 0.0.0.0/0 md5' >> "
            "$(ls -d /etc/postgresql/*/main)/pg_hba.conf"))
        control.exec_("service", "postgresql", "restart")
        cu.await_tcp_port(PORT, host=node)
        control.exec_(control.lit(
            f"su postgres -c \"psql -c \\\"CREATE USER {DB_USER} WITH "
            f"PASSWORD '{DB_PASS}'\\\"\" || true"))
        control.exec_(control.lit(
            f"su postgres -c \"createdb -O {DB_USER} {DB_NAME}\" || true"))

    def teardown(self, test, node):
        control.exec_(control.lit(
            "service postgresql stop >/dev/null 2>&1 || true"))
        control.exec_(control.lit(
            f"su postgres -c \"dropdb --if-exists {DB_NAME}\" "
            ">/dev/null 2>&1 || true"))

    def start(self, test, node):
        control.exec_("service", "postgresql", "start")

    def kill(self, test, node):
        cu.grepkill("postgres")

    def pause(self, test, node):
        cu.grepkill("postgres", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("postgres", sig="CONT")

    def log_files(self, test, node):
        return [LOG]


class PostgresClient(PGSuiteClient):
    """The postgres-rds single-endpoint shape of the shared PG suite
    client (``_pg_client.py``): every node runs an independent
    unreplicated server, so all clients share the first node's instance
    — otherwise reads on n2 could never see writes on n1 and checkers
    would flag a healthy deployment.

    Class attributes stay overridable (the wire tests subclass with
    their own endpoint/credentials)."""

    PORT = PORT
    DB_NAME, DB_USER, DB_PASS = DB_NAME, DB_USER, DB_PASS

    def __init__(self, isolation: str = "serializable",
                 timeout_s: float = 5.0, node: str | None = None):
        super().__init__(
            port=self.PORT, database=self.DB_NAME, user=self.DB_USER,
            password=self.DB_PASS, isolation=isolation,
            endpoint_mode="first", timeout_s=timeout_s, node=node)

    def open(self, test, node):
        c = type(self)(self.isolation, self.timeout_s, node)
        c._connect(test)
        return c


SUPPORTED_WORKLOADS = ("append", "register", "set", "bank", "dirty-reads",
                       "monotonic", "sequential")


def postgres_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="postgres",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": PostgresDB(),
            "client": PostgresClient(o.get("isolation", "serializable")),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(postgres_test, extra_keys=("isolation",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--isolation", default="serializable",
                        choices=["read-committed", "repeatable-read",
                                 "serializable"])),
    name="jepsen-postgres")


if __name__ == "__main__":
    import sys
    sys.exit(main())
