"""PostgreSQL test suite (reference: postgres-rds/ and the stolon/
percona/galera SQL suites in jaydenwen123/jepsen — transactional SQL
stores probed for serializability anomalies).

The flagship workload is Elle-style **list-append**: each op is one SQL
transaction of reads (``SELECT elems``) and appends
(``INSERT ... ON CONFLICT ... SET elems = elems || v``) at the chosen
isolation level; the cycle checker then hunts G0/G1/G-single/G2
anomalies in the dependency graph. Register/set workloads map to a
keyed table with UPDATE-guarded compare-and-set.

The client rides the bundled wire-protocol implementation
(``suites/_postgres.py``) — no third-party driver. ``--fake`` swaps in
the in-memory doubles — including the append workload, which the fake
store applies atomically, so the Elle checker path is exercised
end-to-end without a cluster. DB automation installs the distro
postgresql, opens it to the test network, and creates the jepsen
database.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._postgres import (PGConnection, PgError,
                                         SERIALIZATION_FAILURE,
                                         DEADLOCK_DETECTED, parse_int_array)

logger = logging.getLogger("jepsen.postgres")

PORT = 5432
DB_NAME = "jepsen"
DB_USER = "jepsen"
DB_PASS = "jepsenpw"
CONF_DIR = "/etc/postgresql"
LOG = "/var/log/postgresql/postgresql.log"


class PostgresDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Single-node-per-host distro postgres (postgres-rds tests managed
    instances; here each node runs its own server and clients bind to
    their node, the stolon-without-replication shape)."""

    def setup(self, test, node):
        logger.info("%s: installing postgresql", node)
        from jepsen_tpu import os_setup
        os_setup.install(["postgresql", "postgresql-client"])
        # listen beyond localhost + trust the test network (test rig only)
        control.exec_(control.lit(
            "echo \"listen_addresses = '*'\" >> "
            "$(ls -d /etc/postgresql/*/main)/conf.d/jepsen.conf 2>/dev/null "
            "|| echo \"listen_addresses = '*'\" >> "
            "$(ls -d /etc/postgresql/*/main)/postgresql.conf"))
        control.exec_(control.lit(
            "echo 'host all all 0.0.0.0/0 md5' >> "
            "$(ls -d /etc/postgresql/*/main)/pg_hba.conf"))
        control.exec_("service", "postgresql", "restart")
        cu.await_tcp_port(PORT, host=node)
        control.exec_(control.lit(
            f"su postgres -c \"psql -c \\\"CREATE USER {DB_USER} WITH "
            f"PASSWORD '{DB_PASS}'\\\"\" || true"))
        control.exec_(control.lit(
            f"su postgres -c \"createdb -O {DB_USER} {DB_NAME}\" || true"))

    def teardown(self, test, node):
        control.exec_(control.lit(
            "service postgresql stop >/dev/null 2>&1 || true"))
        control.exec_(control.lit(
            f"su postgres -c \"dropdb --if-exists {DB_NAME}\" "
            ">/dev/null 2>&1 || true"))

    def start(self, test, node):
        control.exec_("service", "postgresql", "start")

    def kill(self, test, node):
        cu.grepkill("postgres")

    def pause(self, test, node):
        cu.grepkill("postgres", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("postgres", sig="CONT")

    def log_files(self, test, node):
        return [LOG]


SCHEMA = """
CREATE TABLE IF NOT EXISTS registers (k int PRIMARY KEY, v int);
CREATE TABLE IF NOT EXISTS sets (elem int PRIMARY KEY);
CREATE TABLE IF NOT EXISTS lists (k int PRIMARY KEY, elems int[] NOT NULL DEFAULT '{}');
"""


class PostgresClient(Client):
    """SQL client for register/set/append workloads over the bundled
    wire-protocol connection (suites/_postgres.py)."""

    PORT = PORT
    DB_NAME, DB_USER, DB_PASS = DB_NAME, DB_USER, DB_PASS

    def __init__(self, isolation: str = "serializable",
                 timeout_s: float = 5.0, node: str | None = None):
        self.isolation = isolation
        self.timeout_s = timeout_s
        self.node = node
        self.conn: PGConnection | None = None
        self._broken = False

    def endpoint(self, test, node) -> tuple[str, int]:
        # every node runs an independent unreplicated server, so all
        # clients share the first node's instance — otherwise reads on n2
        # could never see writes on n1 and checkers would flag a healthy
        # deployment (the postgres-rds single-endpoint shape)
        return (test.get("nodes") or [node])[0], self.PORT

    def open(self, test, node):
        c = type(self)(self.isolation, self.timeout_s, node)
        host, port = c.endpoint(test, node)
        c.conn = PGConnection(
            host=host, port=port, database=self.DB_NAME, user=self.DB_USER,
            password=self.DB_PASS, timeout_s=self.timeout_s)
        return c

    def setup(self, test):
        self.conn.query(SCHEMA)

    def _txn_body(self, micro_ops):
        out = []
        for f, k, v in micro_ops:
            if f == "r":
                rows, _ = self.conn.query(
                    f"SELECT elems FROM lists WHERE k = {int(k)}")
                out.append(["r", k,
                            parse_int_array(rows[0][0]) if rows else []])
            elif f == "append":
                self.conn.query(
                    f"INSERT INTO lists (k, elems) VALUES ({int(k)}, "
                    f"ARRAY[{int(v)}]) ON CONFLICT (k) DO UPDATE "
                    f"SET elems = lists.elems || {int(v)}")
                out.append(["append", k, v])
        return out

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if self._broken:
            # a timed-out/failed socket is desynced (leftover response
            # bytes would be parsed as the next query's result); the
            # interpreter only reopens clients on "info" completions, so
            # reconnect here before touching the wire again
            self.close(test)
            host, port = self.endpoint(test, self.node)
            self.conn = PGConnection(
                host=host, port=port, database=self.DB_NAME,
                user=self.DB_USER, password=self.DB_PASS,
                timeout_s=self.timeout_s)
            self._broken = False
        try:
            if f == "txn":
                level = self.isolation.upper().replace("-", " ")
                self.conn.query(f"BEGIN ISOLATION LEVEL {level}")
                try:
                    out = self._txn_body(v)
                    self.conn.query("COMMIT")
                    return {**op, "type": "ok", "value": out}
                except PgError as e:
                    try:
                        self.conn.query("ROLLBACK")
                    except (PgError, OSError):
                        pass
                    if e.sqlstate in (SERIALIZATION_FAILURE,
                                      DEADLOCK_DETECTED):
                        return {**op, "type": "fail",
                                "error": ["serialization-failure", e.msg]}
                    raise
            if f == "add":
                self.conn.query(f"INSERT INTO sets (elem) VALUES ({int(v)}) "
                                "ON CONFLICT DO NOTHING")
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                rows, _ = self.conn.query("SELECT elem FROM sets ORDER BY elem")
                return {**op, "type": "ok",
                        "value": [int(r[0]) for r in rows]}
            if f == "read":
                k, _ = v
                rows, _ = self.conn.query(
                    f"SELECT v FROM registers WHERE k = {int(k)}")
                val = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                return {**op, "type": "ok", "value": [k, val]}
            if f == "write":
                k, val = v
                self.conn.query(
                    f"INSERT INTO registers (k, v) VALUES ({int(k)}, "
                    f"{int(val)}) ON CONFLICT (k) DO UPDATE SET v = {int(val)}")
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                _, tag = self.conn.query(
                    f"UPDATE registers SET v = {int(new)} "
                    f"WHERE k = {int(k)} AND v = {int(old)}")
                ok = self.conn.rowcount(tag) == 1
                return {**op, "type": "ok" if ok else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except OSError as e:
            self._broken = True
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            except Exception:  # noqa: BLE001
                pass


SUPPORTED_WORKLOADS = ("append", "register", "set")


def postgres_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="postgres",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": PostgresDB(),
            "client": PostgresClient(o.get("isolation", "serializable")),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(postgres_test, extra_keys=("isolation",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--isolation", default="serializable",
                        choices=["read-committed", "repeatable-read",
                                 "serializable"])),
    name="jepsen-postgres")


if __name__ == "__main__":
    import sys
    sys.exit(main())
