"""PostgreSQL test suite (reference: postgres-rds/ and the stolon/
percona/galera SQL suites in jaydenwen123/jepsen — transactional SQL
stores probed for serializability anomalies).

The flagship workload is Elle-style **list-append**: each op is one SQL
transaction of reads (``SELECT elems``) and appends
(``INSERT ... ON CONFLICT ... SET elems = elems || v``) at the chosen
isolation level; the cycle checker then hunts G0/G1/G-single/G2
anomalies in the dependency graph. Register/set workloads map to a
keyed table with UPDATE-guarded compare-and-set.

The client needs psycopg2 (not bundled); without it the suite still
composes and runs with ``--fake`` in-memory doubles — including the
append workload, which the fake store applies atomically, so the Elle
checker path is exercised end-to-end without a cluster. DB automation
installs the distro postgresql, opens it to the test network, and
creates the jepsen database.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)

logger = logging.getLogger("jepsen.postgres")

PORT = 5432
DB_NAME = "jepsen"
DB_USER = "jepsen"
DB_PASS = "jepsenpw"
CONF_DIR = "/etc/postgresql"
LOG = "/var/log/postgresql/postgresql.log"


class PostgresDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Single-node-per-host distro postgres (postgres-rds tests managed
    instances; here each node runs its own server and clients bind to
    their node, the stolon-without-replication shape)."""

    def setup(self, test, node):
        logger.info("%s: installing postgresql", node)
        from jepsen_tpu import os_setup
        os_setup.install(["postgresql", "postgresql-client"])
        # listen beyond localhost + trust the test network (test rig only)
        control.exec_(control.lit(
            "echo \"listen_addresses = '*'\" >> "
            "$(ls -d /etc/postgresql/*/main)/conf.d/jepsen.conf 2>/dev/null "
            "|| echo \"listen_addresses = '*'\" >> "
            "$(ls -d /etc/postgresql/*/main)/postgresql.conf"))
        control.exec_(control.lit(
            "echo 'host all all 0.0.0.0/0 md5' >> "
            "$(ls -d /etc/postgresql/*/main)/pg_hba.conf"))
        control.exec_("service", "postgresql", "restart")
        cu.await_tcp_port(PORT, host=node)
        control.exec_(control.lit(
            f"su postgres -c \"psql -c \\\"CREATE USER {DB_USER} WITH "
            f"PASSWORD '{DB_PASS}'\\\"\" || true"))
        control.exec_(control.lit(
            f"su postgres -c \"createdb -O {DB_USER} {DB_NAME}\" || true"))

    def teardown(self, test, node):
        control.exec_(control.lit(
            "service postgresql stop >/dev/null 2>&1 || true"))
        control.exec_(control.lit(
            f"su postgres -c \"dropdb --if-exists {DB_NAME}\" "
            ">/dev/null 2>&1 || true"))

    def start(self, test, node):
        control.exec_("service", "postgresql", "start")

    def kill(self, test, node):
        cu.grepkill("postgres")

    def pause(self, test, node):
        cu.grepkill("postgres", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("postgres", sig="CONT")

    def log_files(self, test, node):
        return [LOG]


SCHEMA = """
CREATE TABLE IF NOT EXISTS registers (k int PRIMARY KEY, v int);
CREATE TABLE IF NOT EXISTS sets (elem int PRIMARY KEY);
CREATE TABLE IF NOT EXISTS lists (k int PRIMARY KEY, elems int[] NOT NULL DEFAULT '{}');
"""


class PostgresClient(Client):
    """SQL client for register/set/append workloads. Requires psycopg2;
    the suite's --fake mode runs without it."""

    def __init__(self, isolation: str = "serializable",
                 timeout_s: float = 5.0, node: str | None = None):
        self.isolation = isolation
        self.timeout_s = timeout_s
        self.node = node
        self.conn = None

    def open(self, test, node):
        try:
            import psycopg2
        except ImportError as e:
            raise RuntimeError(
                "psycopg2 is not installed; run this suite with --fake or "
                "install psycopg2 for a real cluster") from e
        # every node runs an independent unreplicated server, so all
        # clients share the first node's instance — otherwise reads on n2
        # could never see writes on n1 and checkers would flag a healthy
        # deployment (the postgres-rds single-endpoint shape)
        primary = (test.get("nodes") or [node])[0]
        c = PostgresClient(self.isolation, self.timeout_s, node)
        c.conn = psycopg2.connect(
            host=primary, port=PORT, dbname=DB_NAME, user=DB_USER,
            password=DB_PASS, connect_timeout=int(self.timeout_s))
        c.conn.autocommit = True
        return c

    def setup(self, test):
        with self.conn.cursor() as cur:
            cur.execute(SCHEMA)

    def _txn_body(self, cur, micro_ops):
        out = []
        for f, k, v in micro_ops:
            if f == "r":
                cur.execute("SELECT elems FROM lists WHERE k = %s", (k,))
                row = cur.fetchone()
                out.append(["r", k, list(row[0]) if row else []])
            elif f == "append":
                cur.execute(
                    "INSERT INTO lists (k, elems) VALUES (%s, ARRAY[%s]) "
                    "ON CONFLICT (k) DO UPDATE "
                    "SET elems = lists.elems || %s", (k, v, v))
                out.append(["append", k, v])
        return out

    def invoke(self, test, op):
        import psycopg2
        f, v = op.get("f"), op.get("value")
        try:
            with self.conn.cursor() as cur:
                if f == "txn":
                    self.conn.autocommit = False
                    try:
                        level = self.isolation.upper().replace("-", " ")
                        cur.execute(f"SET TRANSACTION ISOLATION LEVEL {level}")
                        out = self._txn_body(cur, v)
                        self.conn.commit()
                        return {**op, "type": "ok", "value": out}
                    except psycopg2.errors.SerializationFailure:
                        self.conn.rollback()
                        return {**op, "type": "fail",
                                "error": ["serialization-failure"]}
                    except psycopg2.Error:
                        # any other failure leaves the txn aborted: roll it
                        # back before restoring autocommit (set_session
                        # inside an aborted txn raises, masking the cause)
                        try:
                            self.conn.rollback()
                        except psycopg2.Error:
                            pass
                        raise
                    finally:
                        try:
                            self.conn.autocommit = True
                        except psycopg2.Error:
                            pass
                if f == "add":
                    cur.execute("INSERT INTO sets (elem) VALUES (%s) "
                                "ON CONFLICT DO NOTHING", (v,))
                    return {**op, "type": "ok"}
                if f == "read" and v is None:
                    cur.execute("SELECT elem FROM sets ORDER BY elem")
                    return {**op, "type": "ok",
                            "value": [r[0] for r in cur.fetchall()]}
                if f == "read":
                    k, _ = v
                    cur.execute("SELECT v FROM registers WHERE k = %s", (k,))
                    row = cur.fetchone()
                    return {**op, "type": "ok",
                            "value": [k, row[0] if row else None]}
                if f == "write":
                    k, val = v
                    cur.execute(
                        "INSERT INTO registers (k, v) VALUES (%s, %s) "
                        "ON CONFLICT (k) DO UPDATE SET v = %s", (k, val, val))
                    return {**op, "type": "ok"}
                if f == "cas":
                    k, (old, new) = v
                    cur.execute("UPDATE registers SET v = %s "
                                "WHERE k = %s AND v = %s", (new, k, old))
                    return {**op, "type": "ok" if cur.rowcount == 1 else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except psycopg2.OperationalError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            except Exception:  # noqa: BLE001
                pass


SUPPORTED_WORKLOADS = ("append", "register", "set")


def postgres_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="postgres",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": PostgresDB(),
            "client": PostgresClient(o.get("isolation", "serializable")),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(postgres_test, extra_keys=("isolation",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--isolation", default="serializable",
                        choices=["read-committed", "repeatable-read",
                                 "serializable"])),
    name="jepsen-postgres")


if __name__ == "__main__":
    import sys
    sys.exit(main())
