"""Per-database test suites (reference layer L8, SURVEY.md §1).

Each suite module exposes a test-map constructor plus a CLI ``main``,
composing a DB, a client, a nemesis package, and one of the reusable
workload kits — the shape of e.g.
jepsen/zookeeper/src/jepsen/zookeeper.clj:105-137 and
yugabyte/src/yugabyte/core.clj:74-106 (workloads-as-data sweeps).

``compose_test`` is the shared assembly step: client ops ride the
workload's generator while the nemesis package's generator injects faults
concurrently, the whole thing time-limited, followed by a healing final
phase (nemesis final-generator, then the workload's final-generator for
final reads).
"""
from __future__ import annotations

from typing import Callable

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen


def compose_test(base: dict, workload: dict, nemesis_pkg: dict | None = None,
                 extra_checkers: dict | None = None) -> dict:
    """Merges a workload kit and a nemesis package into a runnable test map.

    Mirrors the standard suite assembly (zookeeper.clj:105-137): main phase
    = clients(workload gen) ∥ nemesis(package gen) under the test's
    time-limit; final phase = package final-generator (heal faults) then
    workload final-generator (e.g. final reads), on clients only.
    """
    test = dict(base)
    time_limit = float(test.get("time_limit", 60))

    main_gens = [gen.clients(workload["generator"])]
    if nemesis_pkg and nemesis_pkg.get("generator") is not None:
        main_gens.append(gen.nemesis_gen(nemesis_pkg["generator"]))
    phase_list = [gen.time_limit(time_limit, gen.any_gen(*main_gens))]

    if nemesis_pkg and nemesis_pkg.get("final_generator") is not None:
        phase_list.append(gen.nemesis_gen(nemesis_pkg["final_generator"]))
    if workload.get("final_generator") is not None:
        phase_list.append(gen.clients(workload["final_generator"]))
    test["generator"] = (phase_list[0] if len(phase_list) == 1
                         else gen.phases(*phase_list))

    checkers = {
        "stats": chk.stats(),
        "exceptions": chk.unhandled_exceptions(),
        "workload": workload["checker"],
    }
    if not test.get("no_perf"):
        checkers["perf"] = chk.perf()
    checkers.update(extra_checkers or {})
    test["checker"] = chk.compose(checkers)

    if nemesis_pkg and nemesis_pkg.get("nemesis") is not None:
        test["nemesis"] = nemesis_pkg["nemesis"]
    return test


def workload_registry() -> dict[str, Callable]:
    """name -> workload-constructor map for sweep runners
    (yugabyte/core.clj:74-118 pattern)."""
    from jepsen_tpu.workloads import (adya, append, bank, causal_reverse,
                                      long_fork, register, set_workload, wr)
    return {
        "register": register.workload,
        "set": set_workload.workload,
        "bank": bank.workload,
        "append": append.workload,
        "wr": wr.workload,
        "long-fork": long_fork.workload,
        "causal-reverse": causal_reverse.workload,
        "adya": adya.workload,
    }
