"""Per-database test suites (reference layer L8, SURVEY.md §1).

Each suite module exposes a test-map constructor plus a CLI ``main``,
composing a DB, a client, a nemesis package, and one of the reusable
workload kits — the shape of e.g.
jepsen/zookeeper/src/jepsen/zookeeper.clj:105-137 and
yugabyte/src/yugabyte/core.clj:74-106 (workloads-as-data sweeps).

``compose_test`` is the shared assembly step: client ops ride the
workload's generator while the nemesis package's generator injects faults
concurrently, the whole thing time-limited, followed by a healing final
phase (nemesis final-generator, then the workload's final-generator for
final reads).
"""
from __future__ import annotations

from typing import Callable

from jepsen_tpu import checker as chk
from jepsen_tpu import generator as gen


def compose_test(base: dict, workload: dict, nemesis_pkg: dict | None = None,
                 extra_checkers: dict | None = None) -> dict:
    """Merges a workload kit and a nemesis package into a runnable test map.

    Mirrors the standard suite assembly (zookeeper.clj:105-137): main phase
    = clients(workload gen) ∥ nemesis(package gen) under the test's
    time-limit; final phase = package final-generator (heal faults) then
    workload final-generator (e.g. final reads), on clients only.
    """
    test = dict(base)
    # workload config keys (e.g. bank's accounts/total-amount, dirty-reads'
    # row count) ride the test map so checkers and op generators see them;
    # base keys win so CLI options still override workload defaults
    for k, v in workload.items():
        if k not in ("generator", "checker", "final_generator") \
                and k not in test:
            test[k] = v
    time_limit = float(test.get("time_limit", 60))

    main_gens = [gen.clients(workload["generator"])]
    if nemesis_pkg and nemesis_pkg.get("generator") is not None:
        main_gens.append(gen.nemesis_gen(nemesis_pkg["generator"]))
    phase_list = [gen.time_limit(time_limit, gen.any_gen(*main_gens))]

    if nemesis_pkg and nemesis_pkg.get("final_generator") is not None:
        phase_list.append(gen.nemesis_gen(nemesis_pkg["final_generator"]))
    if workload.get("final_generator") is not None:
        phase_list.append(gen.clients(workload["final_generator"]))
    test["generator"] = (phase_list[0] if len(phase_list) == 1
                         else gen.phases(*phase_list))

    checkers = {
        "stats": chk.stats(ungated_fs=workload.get("stats_ungated_fs", ())),
        "exceptions": chk.unhandled_exceptions(),
        "workload": workload["checker"],
    }
    if not test.get("no_perf"):
        checkers["perf"] = chk.perf()
    checkers.update(extra_checkers or {})
    test["checker"] = chk.compose(checkers)

    if nemesis_pkg and nemesis_pkg.get("nemesis") is not None:
        test["nemesis"] = nemesis_pkg["nemesis"]
    return test


def build_suite_test(o: dict | None, *, db_name: str,
                     supported_workloads: tuple, make_real: Callable,
                     make_workload: Callable | None = None,
                     extra_workloads: dict | None = None,
                     fake_client: Callable | None = None,
                     fake_db: Callable | None = None,
                     fault_packages: dict | None = None,
                     nemesis_opts: Callable | dict | None = None,
                     defaults: dict | None = None) -> dict:
    """The standard suite test-map constructor shared by every DB suite.

    ``make_real(o) -> {"db": ..., "client": ..., "os": ...}`` supplies the
    real-cluster pieces; ``--fake`` swaps in the in-memory KV doubles over
    the dummy remote (tests.clj:27-67 pattern) — or ``fake_client()``
    when the suite needs its own double. ``make_workload(name, base)``
    overrides the shared workload registry wholesale for suites with
    bespoke routing (e.g. chronos jobs); ``extra_workloads`` is the
    lighter form — a ``{name: workload_fn(base)}`` map consulted before
    the shared registry, for suites whose own probes shadow or extend
    the registry names. ``defaults`` overrides the standard
    concurrency/time_limit/nemesis_interval. Fault classes come from
    ``o["faults"]`` (default: partition on real clusters, none in fake
    mode) and are assembled by the combined nemesis packages.
    ``nemesis_opts`` — a dict, or ``fn(o, base) -> dict`` — merges extra
    keys into the combined-package opts (membership_state_fn,
    clock_rate_binary, ...), so suites can offer the membership and
    clock-rate fault classes.
    """
    from jepsen_tpu.nemesis import combined

    o = dict(o or {})
    d = defaults or {}
    fake = bool(o.get("fake"))
    workload_name = o.get("workload") or supported_workloads[0]
    if workload_name not in supported_workloads:
        raise ValueError(f"{db_name} suite supports workloads "
                         f"{supported_workloads}, not {workload_name!r}")
    ssh = dict(o.get("ssh") or {})
    if fake:  # fake mode always rides the dummy remote
        ssh["dummy"] = True
    base = {
        "name": f"{db_name}-{workload_name}",
        "nodes": o.get("nodes") or ["n1", "n2", "n3", "n4", "n5"],
        "concurrency": o.get("concurrency", d.get("concurrency", 5)),
        "time_limit": o.get("time_limit", d.get("time_limit", 60)),
        "ssh": ssh,
        "accelerator": o.get("accelerator", "auto"),
        "store_dir": o.get("store_dir", "store"),
        "no_perf": o.get("no_perf", False),
        "leave_db_running": o.get("leave_db_running", False),
        # telemetry opts (doc/observability.md) ride into the test map so
        # core.run wires spans/metrics/profiles with no suite-side code
        "trace": o.get("trace", False),
        "metrics_interval": o.get("metrics_interval", 10.0),
        "profile": o.get("profile", False),
    }
    if "metrics" in o:
        base["metrics"] = o["metrics"]
    if fake:
        from jepsen_tpu.fakes import KVClient, KVStore
        from jepsen_tpu.net import NoopNet
        kv = fake_db() if fake_db else KVStore()
        whole_read = {"bank": "bank", "bank-multitable": "bank",
                      "dirty-reads": "dirty"}.get(workload_name, "set")
        txn_style = "wr" if workload_name in ("wr", "long-fork") else "append"
        client = fake_client() if fake_client \
            else KVClient(kv, whole_read=whole_read, txn_style=txn_style)
        base.update(db=kv, client=client, os=None, net=NoopNet())
    else:
        base.update(make_real(o))
        if o.get("os"):  # --os overrides the suite's default OS
            from jepsen_tpu.os_setup import os_by_name
            base["os"] = os_by_name(o["os"])()

    if make_workload is not None:
        workload = make_workload(workload_name, base)
    elif extra_workloads and workload_name in extra_workloads:
        workload = extra_workloads[workload_name](base)
    else:
        workload = workload_registry()[workload_name](
            base, accelerator=base["accelerator"])

    nemesis_pkg = None
    faults = o.get("faults")
    if faults is None:
        faults = set() if fake else {"partition"}
    if faults:
        extra_nem = (nemesis_opts(o, base) if callable(nemesis_opts)
                     else dict(nemesis_opts or {}))
        nemesis_pkg = combined.nemesis_package({
            "db": base["db"], "faults": set(faults),
            "fault_packages": fault_packages,
            "interval": o.get("nemesis_interval",
                              d.get("nemesis_interval", 10.0)),
            **extra_nem})
    return compose_test(base, workload, nemesis_pkg)


def standard_opt_fn(supported_workloads: tuple,
                    extra: Callable | None = None,
                    nemesis_interval: float = 10.0,
                    extra_faults: tuple = (),
                    workload_default: str | None = "__first__") -> Callable:
    """The shared CLI option set for suites (plus per-suite extras).
    ``extra_faults`` extends --fault with the suite's DB-specific
    vocabulary (e.g. cockroach's skew family, yugabyte's kill-master).
    ``workload_default=None`` leaves --workload unset when omitted — for
    suites whose default depends on another option (yugabyte's --api)."""
    if workload_default == "__first__":
        workload_default = supported_workloads[0]

    def opt_fn(p):
        p.add_argument("--workload", default=workload_default,
                       choices=list(supported_workloads))
        p.add_argument("--fake", action="store_true",
                       help="in-memory client/DB over the dummy remote")
        p.add_argument("--fault", action="append", dest="faults",
                       choices=["partition", "kill", "pause", "clock",
                                *extra_faults])
        p.add_argument("--nemesis-interval", type=float,
                       default=nemesis_interval)
        p.add_argument("--no-perf", action="store_true")
        from jepsen_tpu.os_setup import OS_REGISTRY
        p.add_argument("--os", choices=sorted(OS_REGISTRY),
                       help="override the suite's node OS automation")
        if extra:
            extra(p)
    return opt_fn


def standard_test_fn(suite_test: Callable,
                     extra_keys: tuple = ()) -> Callable:
    """Adapts argparse opts into the suite constructor's option dict."""
    from jepsen_tpu import cli

    def test_fn(opts):
        base = cli.test_opts_to_test(opts, {})
        o = {
            "nodes": base["nodes"],
            "concurrency": base["concurrency"],
            "time_limit": base["time_limit"],
            "ssh": base["ssh"],
            "accelerator": base["accelerator"],
            "store_dir": base["store_dir"],
            "workload": opts.workload,
            "fake": opts.fake or (base["ssh"] or {}).get("dummy", False),
            "faults": set(opts.faults) if opts.faults else None,
            "nemesis_interval": opts.nemesis_interval,
            "no_perf": opts.no_perf,
            "os": getattr(opts, "os", None),
            "trace": base.get("trace", False),
            "metrics_interval": base.get("metrics_interval", 10.0),
            "profile": base.get("profile", False),
        }
        if "metrics" in base:
            o["metrics"] = base["metrics"]
        for k in extra_keys:
            o[k] = getattr(opts, k)
        return suite_test(o)
    return test_fn


def standard_test_all(suite_test_fn: Callable, supported_workloads: tuple,
                      name: str) -> Callable:
    """A ``test-all`` sweep main for a suite: every supported workload
    once per round, from the shared CLI options (cli.clj:429-515; the
    yugabyte sweep generalized)."""
    from jepsen_tpu import cli

    def all_tests(opts) -> list:
        base = cli.test_opts_to_test(opts, {})
        # carry the WHOLE option map — cherry-picking keys silently
        # drops any option later added to test_opts_to_test
        fake = (base.get("ssh") or {}).get("dummy", False)
        return [suite_test_fn(dict(base, workload=w, fake=fake))
                for w in supported_workloads]

    return cli.test_all_cmd(all_tests, name=name)


def suite_registry() -> dict[str, Callable]:
    """name -> test-map-constructor for every bundled DB suite (the
    reference's L8 layer; each also has a CLI ``main``)."""
    from jepsen_tpu.suites import (aerospike, chronos, cockroachdb, consul,
                                   crate, dgraph, disque, elasticsearch,
                                   etcd, faunadb, galera, hazelcast, ignite,
                                   logcabin, mongodb, mysql_cluster, percona,
                                   postgres, rabbitmq, raftis, redis,
                                   rethinkdb, robustirc, stolon, tidb,
                                   yugabyte, zookeeper)
    return {
        "etcd": etcd.etcd_test,
        "zookeeper": zookeeper.zookeeper_test,
        "consul": consul.consul_test,
        "redis": redis.redis_test,
        "postgres": postgres.postgres_test,
        "mongodb": mongodb.mongodb_test,
        "elasticsearch": elasticsearch.elasticsearch_test,
        "crate": crate.crate_test,
        "dgraph": dgraph.dgraph_test,
        "ignite": ignite.ignite_test,
        "hazelcast": hazelcast.hazelcast_test,
        "chronos": chronos.chronos_test,
        "raftis": raftis.raftis_test,
        "disque": disque.disque_test,
        "galera": galera.galera_test,
        "percona": percona.percona_test,
        "mysql-cluster": mysql_cluster.mysql_cluster_test,
        "tidb": tidb.tidb_test,
        "cockroachdb": cockroachdb.cockroachdb_test,
        "stolon": stolon.stolon_test,
        "yugabyte": yugabyte.yugabyte_test,
        "faunadb": faunadb.faunadb_test,
        "robustirc": robustirc.robustirc_test,
        "logcabin": logcabin.logcabin_test,
        "rabbitmq": rabbitmq.rabbitmq_test,
        "rethinkdb": rethinkdb.rethinkdb_test,
        "aerospike": aerospike.aerospike_test,
    }


def workload_registry() -> dict[str, Callable]:
    """name -> workload-constructor map for sweep runners
    (yugabyte/core.clj:74-118 pattern)."""
    from jepsen_tpu.workloads import (adya, append, bank, causal,
                                      causal_reverse, comments, counter,
                                      default_value, dirty_read,
                                      dirty_reads, long_fork,
                                      lost_updates, monotonic,
                                      multi_key_acid, mutex, pages,
                                      queue_workload,
                                      register, sequential, set_workload,
                                      single_key_acid, table_workload,
                                      upsert, version_divergence, wr)
    return {
        "register": register.workload,
        "set": set_workload.workload,
        "bank": bank.workload,
        "append": append.workload,
        "wr": wr.workload,
        "long-fork": long_fork.workload,
        "causal": causal.workload,
        "causal-reverse": causal_reverse.workload,
        "adya": adya.workload,
        "queue": queue_workload.workload,
        "dirty-reads": dirty_reads.workload,
        "monotonic": monotonic.workload,
        "sequential": sequential.workload,
        "mutex": mutex.workload,
        "counter": counter.workload,
        "single-key-acid": single_key_acid.workload,
        "multi-key-acid": multi_key_acid.workload,
        "default-value": default_value.workload,
        "comments": comments.workload,
        "table": table_workload.workload,
        "upsert": upsert.workload,
        "lost-updates": lost_updates.workload,
        "version-divergence": version_divergence.workload,
        "dirty-read": dirty_read.workload,
        "pages": pages.workload,
    }
