"""Minimal Apache Ignite thin-client binary protocol for the ignite
suite's transactional bank workload (reference:
ignite/src/jepsen/ignite/bank.clj rides the full Java client's
TRANSACTIONAL cache txns; this is the from-scratch wire equivalent —
the same playbook as the CQL/RESP/AMQP/hazelcast clients here).

Protocol shape (the "Binary Client Protocol", default port 10800):

- **Handshake**: ``length(le i32) | 1 | major(le i16) | minor | patch |
  2`` (client code); success response is a single 1 byte after the
  length. Version 1.6.0 is negotiated — the first revision carrying
  client transactions (OP_TX_START/OP_TX_END).
- **Requests**: ``length | op_code(le i16) | request_id(le i64) |
  payload``; responses echo the request id and carry a status (0 = ok,
  else an error string follows).
- **Values** travel as binary data objects: a type-code byte + the
  value — here longs (4), ints (3), strings (9) and NULL (101).
- **Cache ops** address caches by the Java ``String.hashCode`` of the
  cache name, then a flags byte; flag 0x02 marks the op transactional
  and is followed by the ambient transaction id (le i32) from
  OP_TX_START. The suite pre-declares the TRANSACTIONAL cache in the
  server XML, so no cache-configuration codec is needed.

Ops: OP_CACHE_GET 1000, OP_CACHE_PUT 1001, OP_CACHE_GET_ALL 1003,
OP_TX_START 4000, OP_TX_END 4001.
"""
from __future__ import annotations

import itertools
import socket
import struct
import threading

from jepsen_tpu.suites._wire import close_quietly, recv_exact

OP_CACHE_GET = 1000
OP_CACHE_PUT = 1001
OP_CACHE_GET_ALL = 1003
OP_TX_START = 4000
OP_TX_END = 4001

TYPE_BYTE = 1
TYPE_SHORT = 2
TYPE_INT = 3
TYPE_LONG = 4
TYPE_BOOL = 8
TYPE_STRING = 9
TYPE_NULL = 101

CONCURRENCY = {"optimistic": 1, "pessimistic": 2}
ISOLATION = {"read-committed": 1, "repeatable-read": 2, "serializable": 3}

FLAG_TRANSACTIONAL = 0x02


def java_hash(s: str) -> int:
    """Java String.hashCode (cache ids are the name's hash)."""
    h = 0
    for ch in s:
        h = (31 * h + ord(ch)) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def obj_long(v: int) -> bytes:
    return struct.pack("<bq", TYPE_LONG, v)


def obj_string(s: str | None) -> bytes:
    if s is None:
        return struct.pack("<b", TYPE_NULL)
    b = s.encode("utf-8")
    return struct.pack("<bi", TYPE_STRING, len(b)) + b


def read_obj(buf: bytes, off: int):
    """Decodes one data object; returns (value, next offset)."""
    code = buf[off]
    off += 1
    if code == TYPE_NULL:
        return None, off
    if code == TYPE_LONG:
        return struct.unpack_from("<q", buf, off)[0], off + 8
    if code == TYPE_INT:
        return struct.unpack_from("<i", buf, off)[0], off + 4
    if code == TYPE_SHORT:
        return struct.unpack_from("<h", buf, off)[0], off + 2
    if code == TYPE_BYTE:
        return struct.unpack_from("<b", buf, off)[0], off + 1
    if code == TYPE_BOOL:
        return bool(buf[off]), off + 1
    if code == TYPE_STRING:
        n = struct.unpack_from("<i", buf, off)[0]
        off += 4
        return buf[off:off + n].decode("utf-8"), off + n
    raise IgniteError(-1, f"unsupported data-object type {code}")


class IgniteError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"ignite status {status}: {message}")
        self.status = status
        self.message = message


class ThinClient:
    """One authenticated thin-client connection, single in-flight
    request (one client per logical process)."""

    VERSION = (1, 6, 0)

    def __init__(self, host: str, port: int = 10800,
                 timeout_s: float = 10.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.sock: socket.socket | None = None
        self._req = itertools.count(1)
        self._lock = threading.Lock()
        self.tx_id: int | None = None   # ambient transaction

    def connect(self) -> "ThinClient":
        self.tx_id = None
        self.sock = socket.create_connection((self.host, self.port),
                                             timeout=self.timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        body = struct.pack("<bhhhb", 1, *self.VERSION, 2)
        self.sock.sendall(struct.pack("<i", len(body)) + body)
        n = struct.unpack("<i", recv_exact(self.sock, 4))[0]
        resp = recv_exact(self.sock, n)
        if not resp or resp[0] != 1:
            # failure payload: server version + error string
            msg = ""
            if len(resp) > 7:
                try:
                    msg, _ = read_obj(resp, 7)
                except Exception:  # noqa: BLE001
                    pass
            raise IgniteError(-1, f"handshake rejected: {msg}")
        return self

    def close(self):
        close_quietly(self.sock)
        self.sock = None
        self.tx_id = None

    def request(self, op_code: int, payload: bytes) -> bytes:
        if self.sock is None:
            raise ConnectionError("not connected")
        rid = next(self._req)
        body = struct.pack("<hq", op_code, rid) + payload
        with self._lock:
            self.sock.sendall(struct.pack("<i", len(body)) + body)
            while True:
                n = struct.unpack("<i", recv_exact(self.sock, 4))[0]
                resp = recv_exact(self.sock, n)
                got_rid, status = struct.unpack_from("<qi", resp, 0)
                if got_rid != rid:
                    continue  # stale response from an abandoned retry
                if status != 0:
                    try:
                        msg, _ = read_obj(resp, 12)
                    except Exception:  # noqa: BLE001
                        msg = "<undecodable>"
                    raise IgniteError(status, str(msg))
                return resp[12:]

    # -- cache ops ----------------------------------------------------------

    def _cache_header(self, cache: str) -> bytes:
        flags, tail = 0, b""
        if self.tx_id is not None:
            flags |= FLAG_TRANSACTIONAL
            tail = struct.pack("<i", self.tx_id)
        return struct.pack("<ib", java_hash(cache), flags) + tail

    def cache_get(self, cache: str, key: int):
        out = self.request(OP_CACHE_GET,
                           self._cache_header(cache) + obj_long(key))
        return read_obj(out, 0)[0]

    def cache_put(self, cache: str, key: int, value: int) -> None:
        self.request(OP_CACHE_PUT, self._cache_header(cache)
                     + obj_long(key) + obj_long(value))

    def cache_get_all(self, cache: str, keys: list[int]) -> dict:
        payload = self._cache_header(cache) + struct.pack("<i", len(keys))
        for k in keys:
            payload += obj_long(k)
        out = self.request(OP_CACHE_GET_ALL, payload)
        count = struct.unpack_from("<i", out, 0)[0]
        off = 4
        result = {}
        for _ in range(count):
            k, off = read_obj(out, off)
            v, off = read_obj(out, off)
            result[k] = v
        return result

    # -- transactions -------------------------------------------------------

    def tx_start(self, concurrency: str = "pessimistic",
                 isolation: str = "repeatable-read",
                 timeout_ms: int = 3000, label: str | None = None) -> int:
        payload = struct.pack("<bbq", CONCURRENCY[concurrency],
                              ISOLATION[isolation], timeout_ms)
        payload += obj_string(label)
        out = self.request(OP_TX_START, payload)
        self.tx_id = struct.unpack_from("<i", out, 0)[0]
        return self.tx_id

    def tx_end(self, committed: bool) -> None:
        tx, self.tx_id = self.tx_id, None
        if tx is None:
            return
        self.request(OP_TX_END, struct.pack("<ib", tx, committed))
