"""Minimal Aerospike wire client for the aerospike suite (reference:
aerospike/src/aerospike/ rides the official Java client; this is the
from-scratch equivalent for the CAS-register workload).

Two sub-protocols share an 8-byte ``version(1) type(1) length(6)``
envelope:

- **info** (type 1): newline-terminated request names, tab-separated
  replies — used for cluster administration.
- **message** (type 3): a 22-byte header (info bits, result code,
  generation, ttl, field/op counts) followed by fields (namespace,
  set, key digest) and bin operations — used for reads and writes.

Single-record transactions address records by a RIPEMD-160 digest of
``set + key-type + key`` which the *client* computes; OpenSSL 3 ships
ripemd160 only in the legacy provider, so a pure-Python implementation
(verified against the published test vectors) is included.

Compare-and-set uses Aerospike's generation policy: read returns the
record's generation counter, and a write carrying that generation with
the GENERATION info bit set is rejected with GENERATION_ERROR if the
record changed in between — the same optimistic-CAS scheme the
reference's cas_register client uses.
"""
from __future__ import annotations

import socket
import struct

# -- RIPEMD-160 (pure python; test vectors in tests/test_wire_suites.py) ----

def _rol(x, n):
    x &= 0xFFFFFFFF
    return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF


_R1 = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
       7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
       3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
       1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
       4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13]
_R2 = [5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
       6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
       15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
       8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
       12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11]
_S1 = [11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
       7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
       11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
       11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
       9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6]
_S2 = [8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
       9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
       9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
       15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
       8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11]
_K1 = [0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E]
_K2 = [0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000]


def _f(j, x, y, z):
    if j < 16:
        return x ^ y ^ z
    if j < 32:
        return (x & y) | (~x & z)
    if j < 48:
        return (x | ~y) ^ z
    if j < 64:
        return (x & z) | (y & ~z)
    return x ^ (y | ~z)


def ripemd160(data: bytes) -> bytes:
    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    # MD4-style padding: 0x80, zeros, 64-bit little-endian bit length
    ml = len(data) * 8
    data = data + b"\x80"
    data += b"\x00" * ((56 - len(data) % 64) % 64)
    data += struct.pack("<Q", ml)
    for off in range(0, len(data), 64):
        x = struct.unpack("<16I", data[off:off + 64])
        a1, b1, c1, d1, e1 = h
        a2, b2, c2, d2, e2 = h
        for j in range(80):
            t = _rol((a1 + _f(j, b1, c1, d1) + x[_R1[j]] + _K1[j // 16]),
                     _S1[j]) + e1
            a1, e1, d1, c1, b1 = e1, d1, _rol(c1, 10), b1, t & 0xFFFFFFFF
            t = _rol((a2 + _f(79 - j, b2, c2, d2) + x[_R2[j]]
                      + _K2[j // 16]), _S2[j]) + e2
            a2, e2, d2, c2, b2 = e2, d2, _rol(c2, 10), b2, t & 0xFFFFFFFF
        t = (h[1] + c1 + d2) & 0xFFFFFFFF
        h[1] = (h[2] + d1 + e2) & 0xFFFFFFFF
        h[2] = (h[3] + e1 + a2) & 0xFFFFFFFF
        h[3] = (h[4] + a1 + b2) & 0xFFFFFFFF
        h[4] = (h[0] + b1 + c2) & 0xFFFFFFFF
        h[0] = t
    return struct.pack("<5I", *h)


# -- wire constants ---------------------------------------------------------

PROTO_VERSION = 2
TYPE_INFO = 1
TYPE_MESSAGE = 3

# message header info bits
INFO1_READ = 0x01
INFO1_GET_ALL = 0x02
INFO2_WRITE = 0x01
INFO2_GENERATION = 0x04     # write only if generation matches

# field types
FIELD_NAMESPACE = 0
FIELD_SET = 1
FIELD_DIGEST = 4

# bin operations / particle types
OP_READ = 1
OP_WRITE = 2
OP_INCR = 5
OP_APPEND = 9
PARTICLE_INTEGER = 1
PARTICLE_STRING = 3

# result codes (aerospike server)
RC_OK = 0
RC_KEY_NOT_FOUND = 2
RC_GENERATION_ERROR = 3

KEY_TYPE_INTEGER = 1


class AerospikeError(Exception):
    def __init__(self, code: int):
        super().__init__(f"result code {code}")
        self.code = code


def key_digest(set_name: str, key: int) -> bytes:
    """RIPEMD-160 of set + key-type byte + big-endian key bytes — the
    digest every Aerospike client computes for integer keys."""
    return ripemd160(set_name.encode() + bytes([KEY_TYPE_INTEGER])
                     + struct.pack(">q", key))


def _field(ftype: int, data: bytes) -> bytes:
    return struct.pack(">IB", len(data) + 1, ftype) + data


def _op(op_type: int, bin_name: str, data: bytes = b"",
        particle: int = 0) -> bytes:
    name = bin_name.encode()
    return (struct.pack(">IBBBB", 4 + len(name) + len(data),
                        op_type, particle, 0, len(name)) + name + data)


class AerospikeConnection:
    """One socket to one node; single-record transactions + info."""

    def __init__(self, host: str, port: int = 3000,
                 namespace: str = "test", set_name: str = "jepsen",
                 timeout_s: float = 5.0):
        self.namespace = namespace
        self.set_name = set_name
        self.sock = socket.create_connection((host, port), timeout=timeout_s)

    def _recv_exact(self, n: int) -> bytes:
        from jepsen_tpu.suites._wire import recv_exact
        return recv_exact(self.sock, n)

    def _send(self, mtype: int, payload: bytes) -> bytes:
        size = len(payload)
        header = struct.pack(">Q", (PROTO_VERSION << 56) | (mtype << 48)
                             | size)
        self.sock.sendall(header + payload)
        reply_header = struct.unpack(">Q", self._recv_exact(8))[0]
        reply_size = reply_header & 0xFFFFFFFFFFFF
        return self._recv_exact(reply_size)

    # -- info protocol ----------------------------------------------------

    def info(self, *names: str) -> dict[str, str]:
        """The info sub-protocol (cluster admin; aerospike
        support.clj's asinfo usage)."""
        payload = ("\n".join(names) + "\n").encode()
        reply = self._send(TYPE_INFO, payload).decode()
        out = {}
        for line in reply.split("\n"):
            if "\t" in line:
                k, v = line.split("\t", 1)
                out[k] = v
        return out

    # -- single-record transactions --------------------------------------

    def _message(self, info1: int, info2: int, generation: int,
                 ops: list[bytes], key: int) -> tuple[int, int, bytes]:
        fields = [_field(FIELD_NAMESPACE, self.namespace.encode()),
                  _field(FIELD_SET, self.set_name.encode()),
                  _field(FIELD_DIGEST, key_digest(self.set_name, key))]
        body = (struct.pack(">BBBBBBIIIHH", 22, info1, info2, 0, 0, 0,
                            generation, 0, 1000, len(fields), len(ops))
                + b"".join(fields) + b"".join(ops))
        reply = self._send(TYPE_MESSAGE, body)
        result_code = reply[5]
        r_generation = struct.unpack(">I", reply[6:10])[0]
        n_fields, n_ops = struct.unpack(">HH", reply[18:22])
        pos = 22
        for _ in range(n_fields):
            fsize = struct.unpack(">I", reply[pos:pos + 4])[0]
            pos += 4 + fsize
        bin_data = b""
        for _ in range(n_ops):
            osize = struct.unpack(">I", reply[pos:pos + 4])[0]
            name_len = reply[pos + 7]
            bin_data = reply[pos + 8 + name_len:pos + 4 + osize]
            pos += 4 + osize
        return result_code, r_generation, bin_data

    def get(self, key: int, bin_name: str = "value"):
        """Reads one named bin; returns (value, generation) or (None, 0)
        when the record is absent."""
        rc, gen, data = self._message(INFO1_READ, 0, 0,
                                      [_op(OP_READ, bin_name)], key)
        if rc == RC_KEY_NOT_FOUND:
            return None, 0
        if rc != RC_OK:
            raise AerospikeError(rc)
        value = struct.unpack(">q", data)[0] if len(data) == 8 else None
        return value, gen

    def put(self, key: int, value: int, bin_name: str = "value",
            generation: int | None = None) -> bool:
        """Writes; with ``generation`` set, succeeds only if the record
        still carries that generation (False on GENERATION_ERROR)."""
        info2 = INFO2_WRITE
        gen = 0
        if generation is not None:
            info2 |= INFO2_GENERATION
            gen = generation
        ops = [_op(OP_WRITE, bin_name, struct.pack(">q", value),
                   PARTICLE_INTEGER)]
        rc, _, _ = self._message(0, info2, gen, ops, key)
        if rc == RC_GENERATION_ERROR:
            return False
        if rc != RC_OK:
            raise AerospikeError(rc)
        return True

    def append(self, key: int, text: str, bin_name: str = "value") -> None:
        """Server-side atomic string append (the set workload's
        operate-append, aerospike/set.clj:35 s/append!)."""
        ops = [_op(OP_APPEND, bin_name, text.encode(), PARTICLE_STRING)]
        rc, _, _ = self._message(0, INFO2_WRITE, 0, ops, key)
        if rc != RC_OK:
            raise AerospikeError(rc)

    def get_string(self, key: int, bin_name: str = "value"):
        """Reads one named bin as a string ('' when absent)."""
        rc, _gen, data = self._message(INFO1_READ, 0, 0,
                                       [_op(OP_READ, bin_name)], key)
        if rc == RC_KEY_NOT_FOUND:
            return ""
        if rc != RC_OK:
            raise AerospikeError(rc)
        return data.decode(errors="replace")

    def incr(self, key: int, delta: int, bin_name: str = "value") -> None:
        """Server-side atomic integer add (the counter workload's
        operate-add, aerospike/counter.clj)."""
        ops = [_op(OP_INCR, bin_name, struct.pack(">q", delta),
                   PARTICLE_INTEGER)]
        rc, _, _ = self._message(0, INFO2_WRITE, 0, ops, key)
        if rc != RC_OK:
            raise AerospikeError(rc)

    def close(self) -> None:
        from jepsen_tpu.suites._wire import close_quietly
        close_quietly(self.sock)
