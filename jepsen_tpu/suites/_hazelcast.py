"""Minimal Hazelcast Open Binary Client Protocol (2.x) client for the
hazelcast suite's CP-subsystem workloads (reference:
hazelcast/src/jepsen/hazelcast.clj rides the official Java client; this
is the from-scratch equivalent for the CP AtomicLong / FencedLock /
Semaphore clients, the same playbook as the CQL/RESP/AMQP/MySQL/PG wire
clients in this package).

Protocol shape (Hazelcast 4/5, the ``CP2`` handshake):

- After connect the client sends the 3-byte protocol id ``CP2``; all
  further traffic is **client messages** — sequences of frames, each
  ``length(le u32) | flags(le u16) | payload``, where length counts the
  6-byte header. The first frame of a message starts with message type
  (le u32) and correlation id (le u64); requests add a partition id
  (le u32, -1 for CP ops). Response initial frames carry one
  backup-acks byte after the correlation id.
- Fixed-size request parameters pack into the initial frame in
  declaration order; variable-size parameters (strings, custom types)
  follow as their own frames. Custom types (RaftGroupId here) nest
  between BEGIN/END data-structure frames with their fixed fields in a
  leading frame.
- CP data structures address a **Raft group** (RaftGroupId =
  {name, seed, id}) obtained from ``CPGroup.createCPGroup``; FencedLock
  and Semaphore ops additionally carry a CP **session**
  (``CPSession.createSession``, kept alive by heartbeats), a thread id
  (``CPSession.generateThreadId``) and a per-invocation UUID for
  exactly-once retry semantics.

Message type ids follow the public hazelcast-client-protocol 2.x
protocol definitions (module id in the high byte pair, method in the
middle): Client=0x00, FencedLock=0x07, AtomicLong=0x09, Semaphore=0x0C,
CPGroup=0x1E, CPSession=0x1F. They are centralised in :data:`MSG` so a
deployment against a server revision that renumbers a module is a
one-line audit. The mock-server wire tests
(tests/test_hazelcast.py) speak the same table from the server
side and pin the codec layouts; the realdb-gated test exercises a real
member when one is installed.
"""
from __future__ import annotations

import itertools
import os
import socket
import struct
import threading
import time

from jepsen_tpu.suites._wire import close_quietly, recv_exact

PROTOCOL_ID = b"CP2"

# frame flags
BEGIN_FRAGMENT = 1 << 15
END_FRAGMENT = 1 << 14
UNFRAGMENTED = BEGIN_FRAGMENT | END_FRAGMENT
IS_FINAL = 1 << 13
BEGIN_DATA = 1 << 12
END_DATA = 1 << 11
IS_NULL = 1 << 10
IS_EVENT = 1 << 9

SIZE_OF_FRAME_HEADER = 6
REQUEST_HEADER = 16   # type(4) correlation(8) partition(4)
RESPONSE_HEADER = 13  # type(4) correlation(8) backup-acks(1)

EXCEPTION_MSG_TYPE = 0
INVALID_FENCE = 0

MSG = {
    "client.authentication": 0x000100,
    "map.put": 0x010100,
    "map.get": 0x010200,
    "map.replaceifsame": 0x010500,
    "map.putifabsent": 0x010E00,
    "fencedlock.lock": 0x070100,
    "fencedlock.trylock": 0x070200,
    "fencedlock.unlock": 0x070300,
    "atomiclong.addandget": 0x090300,
    "atomiclong.compareandset": 0x090400,
    "atomiclong.get": 0x090500,
    "atomiclong.getandset": 0x090700,
    "atomicref.compareandset": 0x0A0200,
    "atomicref.get": 0x0A0400,
    "atomicref.set": 0x0A0500,
    "semaphore.init": 0x0C0100,
    "semaphore.acquire": 0x0C0200,
    "semaphore.release": 0x0C0300,
    "flakeidgen.newidbatch": 0x1C0100,
    "cpgroup.createcpgroup": 0x1E0100,
    "cpsession.createsession": 0x1F0100,
    "cpsession.closesession": 0x1F0200,
    "cpsession.heartbeatsession": 0x1F0300,
    "cpsession.generatethreadid": 0x1F0400,
}


class HzError(Exception):
    """Server-side error response (ErrorCodec). ``code`` is the first
    error holder's numeric code, ``class_name`` its Java class."""

    def __init__(self, code: int, class_name: str, message: str):
        super().__init__(f"{class_name}({code}): {message}")
        self.code = code
        self.class_name = class_name
        self.message = message


class Frame:
    __slots__ = ("flags", "payload")

    def __init__(self, payload: bytes, flags: int = 0):
        self.flags = flags
        self.payload = payload

    def is_null(self) -> bool:
        return bool(self.flags & IS_NULL)

    def is_begin(self) -> bool:
        return bool(self.flags & BEGIN_DATA)

    def is_end(self) -> bool:
        return bool(self.flags & END_DATA)


NULL_FRAME = Frame(b"", IS_NULL)
BEGIN_FRAME = Frame(b"", BEGIN_DATA)
END_FRAME = Frame(b"", END_DATA)


def encode_message(frames: list[Frame]) -> bytes:
    """Serializes frames; first gets UNFRAGMENTED, last gets IS_FINAL."""
    out = bytearray()
    last = len(frames) - 1
    for i, f in enumerate(frames):
        flags = f.flags
        if i == 0:
            flags |= UNFRAGMENTED
        if i == last:
            flags |= IS_FINAL
        out += struct.pack("<IH", len(f.payload) + SIZE_OF_FRAME_HEADER,
                           flags)
        out += f.payload
    return bytes(out)


def read_message(sock: socket.socket) -> list[Frame]:
    """Reads frames until one carries IS_FINAL."""
    frames = []
    while True:
        size, flags = struct.unpack("<IH",
                                    recv_exact(sock, SIZE_OF_FRAME_HEADER))
        payload = recv_exact(sock, size - SIZE_OF_FRAME_HEADER)
        frames.append(Frame(payload, flags))
        if flags & IS_FINAL:
            return frames


# -- codec primitives -------------------------------------------------------

def str_frame(s: str) -> Frame:
    return Frame(s.encode("utf-8"))


def nullable_str_frame(s: str | None) -> Frame:
    return NULL_FRAME if s is None else str_frame(s)


def encode_uuid(u: bytes | None) -> bytes:
    """17-byte nullable UUID: is-null bool + 16 raw bytes."""
    if u is None:
        return b"\x01" + b"\x00" * 16
    assert len(u) == 16
    return b"\x00" + u


def random_uuid() -> bytes:
    return os.urandom(16)


def raft_group_frames(group: "RaftGroupId") -> list[Frame]:
    """RaftGroupId custom codec: BEGIN, fixed [seed(8) id(8)], name,
    END."""
    return [BEGIN_FRAME,
            Frame(struct.pack("<qq", group.seed, group.group_id)),
            str_frame(group.name),
            END_FRAME]


class RaftGroupId:
    __slots__ = ("name", "seed", "group_id")

    def __init__(self, name: str, seed: int, group_id: int):
        self.name = name
        self.seed = seed
        self.group_id = group_id

    def __repr__(self):
        return f"RaftGroupId({self.name!r}, {self.seed}, {self.group_id})"


def decode_raft_group(frames: list[Frame], i: int) -> tuple[RaftGroupId, int]:
    """Decodes the custom type starting at frames[i] (a BEGIN frame);
    returns (group, next index). Skips unknown trailing fields until the
    matching END frame (forward-compatible decode)."""
    assert frames[i].is_begin(), "RaftGroupId must start with BEGIN"
    seed, gid = struct.unpack_from("<qq", frames[i + 1].payload, 0)
    name = frames[i + 2].payload.decode("utf-8")
    depth, j = 1, i + 3
    while depth > 0:
        if frames[j].is_begin():
            depth += 1
        elif frames[j].is_end():
            depth -= 1
        j += 1
    return RaftGroupId(name, seed, gid), j


def murmur3_x86_32(data: bytes, seed: int = 0x01000193) -> int:
    """Murmur3 32-bit (hazelcast's default-seed variant) — partition
    routing hashes the key Data's payload with it."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed
    n = len(data)
    rounded = n - (n % 4)
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h - (1 << 32) if h >= (1 << 31) else h


def hash_to_index(hash_: int, length: int) -> int:
    """Java HashUtil.hashToIndex: MIN_VALUE pins to 0, else abs % n."""
    if length <= 0:
        return 0
    if hash_ == -(1 << 31):
        return 0
    return abs(hash_) % length


# -- hazelcast serialization (Data) -----------------------------------------
# Map/AtomicRef values travel as serialized "Data" blobs:
# ``partition-hash(be i32) | type-id(be i32) | payload`` with the
# built-in constant serializer ids (Integer -7, Long -8, String -11,
# long[] -17) and BIG-endian payloads — the one big-endian corner of an
# otherwise little-endian protocol.

TYPE_LONG_JAVA = -8
TYPE_STRING_JAVA = -11
TYPE_LONG_ARRAY_JAVA = -17


def data_long(v: int) -> bytes:
    return struct.pack(">iiq", 0, TYPE_LONG_JAVA, v)


def data_string(s: str) -> bytes:
    b = s.encode("utf-8")
    return struct.pack(">iii", 0, TYPE_STRING_JAVA, len(b)) + b


def data_long_array(vals) -> bytes:
    vals = list(vals)
    return struct.pack(">iii", 0, TYPE_LONG_ARRAY_JAVA, len(vals)) + \
        b"".join(struct.pack(">q", v) for v in vals)


def decode_data(blob: bytes):
    """Decodes the Data types this client writes; anything else returns
    the raw payload bytes (callers treat unknown types opaquely)."""
    if len(blob) < 8:
        return None
    type_id = struct.unpack_from(">i", blob, 4)[0]
    body = blob[8:]
    if type_id == TYPE_LONG_JAVA:
        return struct.unpack(">q", body)[0]
    if type_id == TYPE_STRING_JAVA:
        n = struct.unpack_from(">i", body, 0)[0]
        return body[4:4 + n].decode("utf-8")
    if type_id == TYPE_LONG_ARRAY_JAVA:
        n = struct.unpack_from(">i", body, 0)[0]
        return list(struct.unpack_from(f">{n}q", body, 4)) if n else []
    return body


def decode_error(frames: list[Frame]) -> HzError:
    """ErrorCodec response: a list-of-ErrorHolder data structure; each
    holder = BEGIN, fixed [errorCode(4)], className str, message
    nullable str, stack-trace list, END. Only the first holder's
    essentials are surfaced."""
    try:
        # frames[0] initial; frames[1] list BEGIN; frames[2] holder
        # BEGIN; frames[3] holder initial [errorCode]; then var fields
        code = struct.unpack_from("<i", frames[3].payload, 0)[0]
        class_name = frames[4].payload.decode("utf-8", "replace")
        msg_f = frames[5]
        message = "" if msg_f.is_null() else \
            msg_f.payload.decode("utf-8", "replace")
        return HzError(code, class_name, message)
    except (IndexError, struct.error):
        return HzError(-1, "unknown", "undecodable error response")


# -- the client -------------------------------------------------------------

class HzClient:
    """One TCP connection to a member, authenticated, single in-flight
    invocation (the suite runs one client per logical process, matching
    the generator's thread model — no multiplexing needed)."""

    def __init__(self, host: str, port: int = 5701,
                 cluster_name: str = "jepsen",
                 client_name: str | None = None,
                 timeout_s: float = 10.0):
        self.host = host
        self.port = port
        self.cluster_name = cluster_name
        self.client_name = client_name or f"jepsen-{os.getpid()}"
        self.timeout_s = timeout_s
        self.sock: socket.socket | None = None
        self._correlation = itertools.count(1)
        self._lock = threading.Lock()
        self._groups: dict[str, RaftGroupId] = {}
        self._sessions: dict[tuple[str, int], tuple[int, float, float]] = {}
        self._thread_id: int | None = None
        self.partition_count = 0   # from the auth response

    # -- connection/auth ----------------------------------------------------

    def connect(self) -> "HzClient":
        # a (re)connect is a fresh client to the server: cached groups,
        # CP sessions and the thread id belong to the old connection
        self._groups.clear()
        self._sessions.clear()
        self._thread_id = None
        self.sock = socket.create_connection((self.host, self.port),
                                             timeout=self.timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.sendall(PROTOCOL_ID)
        frames = self._invoke(
            MSG["client.authentication"],
            fixed=encode_uuid(random_uuid()) + b"\x01",  # uuid, ser-version
            var=[str_frame(self.cluster_name),
                 NULL_FRAME,                    # username
                 NULL_FRAME,                    # password
                 str_frame("PYT"),              # client type
                 str_frame("5.3"),              # client hz version
                 str_frame(self.client_name),
                 BEGIN_FRAME, END_FRAME])       # labels: empty list
        status = frames[0].payload[RESPONSE_HEADER]
        if status != 0:
            raise HzError(status, "AuthenticationException",
                          f"status {status}")
        # fixed response fields: status(1) memberUuid(17) serVersion(1)
        # partitionCount(4) ... — the count drives map partition routing
        off = RESPONSE_HEADER + 19
        if len(frames[0].payload) >= off + 4:
            self.partition_count = struct.unpack_from(
                "<i", frames[0].payload, off)[0]
        return self

    def _partition_of(self, key_data: bytes) -> int:
        """Partition id for a key Data blob: murmur3 of the payload
        (header skipped), hashToIndex over the member's partition count.
        -1 (server-side routing refused by real members for map tasks)
        only when the auth response carried no count."""
        if self.partition_count <= 0:
            return -1
        return hash_to_index(murmur3_x86_32(key_data[8:]),
                             self.partition_count)

    def close(self):
        close_quietly(self.sock)
        self.sock = None

    # -- invocation ---------------------------------------------------------

    def _invoke(self, msg_type: int, fixed: bytes = b"",
                var: list[Frame] | None = None,
                partition: int = -1) -> list[Frame]:
        """Sends one request, returns the matching response's frames.
        Events (unsolicited pushes) are skipped; an error response
        raises HzError."""
        if self.sock is None:
            raise ConnectionError("not connected")
        corr = next(self._correlation)
        initial = Frame(struct.pack("<IqI", msg_type, corr,
                                    partition & 0xFFFFFFFF) + fixed)
        msg = encode_message([initial] + (var or []))
        with self._lock:
            self.sock.sendall(msg)
            while True:
                frames = read_message(self.sock)
                if frames[0].flags & IS_EVENT:
                    continue
                rtype, rcorr = struct.unpack_from("<Iq",
                                                  frames[0].payload, 0)
                if rcorr != corr:
                    continue  # stale response from an abandoned retry
                if rtype == EXCEPTION_MSG_TYPE:
                    raise decode_error(frames)
                return frames

    @staticmethod
    def _fixed(frames: list[Frame], fmt: str):
        vals = struct.unpack_from(fmt, frames[0].payload, RESPONSE_HEADER)
        return vals[0] if len(vals) == 1 else vals

    # -- CP plumbing --------------------------------------------------------

    def cp_group(self, proxy_name: str = "default") -> RaftGroupId:
        """Resolves (and caches) the Raft group for a CP proxy name
        ("name@group", default group otherwise)."""
        group_name = proxy_name.split("@", 1)[1] if "@" in proxy_name \
            else "default"
        g = self._groups.get(group_name)
        if g is None:
            frames = self._invoke(MSG["cpgroup.createcpgroup"],
                                  var=[str_frame(group_name)])
            g, _ = decode_raft_group(frames, 1)
            self._groups[group_name] = g
        return g

    def thread_id(self, group: RaftGroupId) -> int:
        if self._thread_id is None:
            frames = self._invoke(MSG["cpsession.generatethreadid"],
                                  var=raft_group_frames(group))
            self._thread_id = self._fixed(frames, "<q")
        return self._thread_id

    def session_id(self, group: RaftGroupId) -> int:
        """Current CP session for the group, creating or refreshing as
        needed (the Java client's background heartbeater, done lazily:
        a heartbeat rides ahead of any op once half the TTL elapsed)."""
        key = (group.name, group.group_id)
        now = time.monotonic()
        entry = self._sessions.get(key)
        if entry is not None:
            sid, ttl_s, last = entry
            if now - last < ttl_s / 2:
                return sid
            try:
                self._invoke(MSG["cpsession.heartbeatsession"],
                             fixed=struct.pack("<q", sid),
                             var=raft_group_frames(group))
                self._sessions[key] = (sid, ttl_s, now)
                return sid
            except HzError:
                del self._sessions[key]  # expired: fall through, recreate
        frames = self._invoke(MSG["cpsession.createsession"],
                              var=raft_group_frames(group)
                              + [str_frame(self.client_name)])
        sid, ttl_ms, _hb = self._fixed(frames, "<qqq")
        self._sessions[key] = (sid, max(ttl_ms / 1000.0, 1.0), now)
        return sid

    def close_session(self, group: RaftGroupId):
        key = (group.name, group.group_id)
        entry = self._sessions.pop(key, None)
        if entry is not None:
            self._invoke(MSG["cpsession.closesession"],
                         fixed=struct.pack("<q", entry[0]),
                         var=raft_group_frames(group))

    # -- AtomicLong ---------------------------------------------------------

    def atomic_add_and_get(self, name: str, delta: int) -> int:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.addandget"],
                              fixed=struct.pack("<q", delta),
                              var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    def atomic_get(self, name: str) -> int:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.get"],
                              var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    def atomic_compare_and_set(self, name: str, expected: int,
                               updated: int) -> bool:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.compareandset"],
                              fixed=struct.pack("<qq", expected, updated),
                              var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    def atomic_get_and_set(self, name: str, value: int) -> int:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.getandset"],
                              fixed=struct.pack("<q", value),
                              var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    # -- FencedLock ---------------------------------------------------------

    def lock_try_lock(self, name: str, timeout_ms: int = 5000) -> int:
        """tryLock: the fencing token, or INVALID_FENCE (0) when the
        wait timed out."""
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["fencedlock.trylock"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid())
            + struct.pack("<q", timeout_ms),
            var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    def lock_unlock(self, name: str) -> bool:
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["fencedlock.unlock"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid()),
            var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    # -- IMap (Data-typed distributed map) ----------------------------------

    def map_get(self, name: str, key: bytes):
        """Decoded value or None (key is a serialized Data blob)."""
        blob = self.map_get_raw(name, key)
        return None if blob is None else decode_data(blob)

    def map_get_raw(self, name: str, key: bytes) -> bytes | None:
        """The stored Data blob itself — replaceIfSame compares
        byte-for-byte, so CAS callers must hand back EXACTLY what the
        server holds."""
        frames = self._invoke(MSG["map.get"],
                              fixed=struct.pack("<q", 1),  # thread id
                              var=[str_frame(name), Frame(key)],
                              partition=self._partition_of(key))
        if len(frames) < 2 or frames[1].is_null():
            return None
        return frames[1].payload

    def map_put(self, name: str, key: bytes, value: bytes):
        """Previous decoded value or None. ttl -1 = map default."""
        frames = self._invoke(MSG["map.put"],
                              fixed=struct.pack("<qq", 1, -1),
                              var=[str_frame(name), Frame(key),
                                   Frame(value)],
                              partition=self._partition_of(key))
        return self._nullable_data(frames)

    def map_put_if_absent(self, name: str, key: bytes, value: bytes):
        """Existing decoded value, or None when this put won."""
        frames = self._invoke(MSG["map.putifabsent"],
                              fixed=struct.pack("<qq", 1, -1),
                              var=[str_frame(name), Frame(key),
                                   Frame(value)],
                              partition=self._partition_of(key))
        return self._nullable_data(frames)

    def map_replace_if_same(self, name: str, key: bytes, expected: bytes,
                            value: bytes) -> bool:
        """Server-side CAS: replace only when the stored Data equals
        ``expected`` byte-for-byte (the reference map workload's
        ``.replace`` three-arg form, hazelcast.clj:469-489)."""
        frames = self._invoke(MSG["map.replaceifsame"],
                              fixed=struct.pack("<q", 1),
                              var=[str_frame(name), Frame(key),
                                   Frame(expected), Frame(value)],
                              partition=self._partition_of(key))
        return bool(self._fixed(frames, "<b"))

    @staticmethod
    def _nullable_data(frames: list[Frame]):
        if len(frames) < 2 or frames[1].is_null():
            return None
        return decode_data(frames[1].payload)

    # -- CP AtomicReference (Data-typed) ------------------------------------

    def atomic_ref_get(self, name: str):
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomicref.get"],
                              var=raft_group_frames(g) + [str_frame(name)])
        return self._nullable_data(frames)

    def atomic_ref_set(self, name: str, value) -> None:
        g = self.cp_group(name)
        blob = data_long(value) if value is not None else None
        self._invoke(MSG["atomicref.set"],
                     var=raft_group_frames(g) + [str_frame(name),
                     NULL_FRAME if blob is None else Frame(blob)])

    def atomic_ref_compare_and_set(self, name: str, expected, value) \
            -> bool:
        """CAS over nullable long refs (the atomic-ref id/cas clients,
        hazelcast.clj:211-249)."""
        g = self.cp_group(name)
        eb = None if expected is None else data_long(expected)
        vb = None if value is None else data_long(value)
        frames = self._invoke(
            MSG["atomicref.compareandset"],
            var=raft_group_frames(g) + [
                str_frame(name),
                NULL_FRAME if eb is None else Frame(eb),
                NULL_FRAME if vb is None else Frame(vb)])
        return bool(self._fixed(frames, "<b"))

    # -- FlakeIdGenerator ---------------------------------------------------

    def flake_id_batch(self, name: str, batch_size: int = 1) \
            -> tuple[int, int, int]:
        """(base, increment, count) — ids are base + k*increment for
        k < count (the id-gen workload's newId, hazelcast.clj:252-264;
        5.x replaced the 3.x IdGenerator with flake ids)."""
        frames = self._invoke(MSG["flakeidgen.newidbatch"],
                              fixed=struct.pack("<i", batch_size),
                              var=[str_frame(name)])
        return self._fixed(frames, "<qqi")

    # -- Semaphore ----------------------------------------------------------

    def semaphore_init(self, name: str, permits: int) -> bool:
        g = self.cp_group(name)
        frames = self._invoke(MSG["semaphore.init"],
                              fixed=struct.pack("<i", permits),
                              var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    def semaphore_acquire(self, name: str, permits: int = 1,
                          timeout_ms: int = 5000) -> bool:
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["semaphore.acquire"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid())
            + struct.pack("<iq", permits, timeout_ms),
            var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    def semaphore_release(self, name: str, permits: int = 1) -> bool:
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["semaphore.release"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid())
            + struct.pack("<i", permits),
            var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b")) if \
            len(frames[0].payload) > RESPONSE_HEADER else True
