"""Minimal Hazelcast Open Binary Client Protocol (2.x) client for the
hazelcast suite's CP-subsystem workloads (reference:
hazelcast/src/jepsen/hazelcast.clj rides the official Java client; this
is the from-scratch equivalent for the CP AtomicLong / FencedLock /
Semaphore clients, the same playbook as the CQL/RESP/AMQP/MySQL/PG wire
clients in this package).

Protocol shape (Hazelcast 4/5, the ``CP2`` handshake):

- After connect the client sends the 3-byte protocol id ``CP2``; all
  further traffic is **client messages** — sequences of frames, each
  ``length(le u32) | flags(le u16) | payload``, where length counts the
  6-byte header. The first frame of a message starts with message type
  (le u32) and correlation id (le u64); requests add a partition id
  (le u32, -1 for CP ops). Response initial frames carry one
  backup-acks byte after the correlation id.
- Fixed-size request parameters pack into the initial frame in
  declaration order; variable-size parameters (strings, custom types)
  follow as their own frames. Custom types (RaftGroupId here) nest
  between BEGIN/END data-structure frames with their fixed fields in a
  leading frame.
- CP data structures address a **Raft group** (RaftGroupId =
  {name, seed, id}) obtained from ``CPGroup.createCPGroup``; FencedLock
  and Semaphore ops additionally carry a CP **session**
  (``CPSession.createSession``, kept alive by heartbeats), a thread id
  (``CPSession.generateThreadId``) and a per-invocation UUID for
  exactly-once retry semantics.

Message type ids follow the public hazelcast-client-protocol 2.x
protocol definitions (module id in the high byte pair, method in the
middle): Client=0x00, FencedLock=0x07, AtomicLong=0x09, Semaphore=0x0C,
CPGroup=0x1E, CPSession=0x1F. They are centralised in :data:`MSG` so a
deployment against a server revision that renumbers a module is a
one-line audit. The mock-server wire tests
(tests/test_hazelcast_wire.py) speak the same table from the server
side and pin the codec layouts; the realdb-gated test exercises a real
member when one is installed.
"""
from __future__ import annotations

import itertools
import os
import socket
import struct
import threading
import time

from jepsen_tpu.suites._wire import close_quietly, recv_exact

PROTOCOL_ID = b"CP2"

# frame flags
BEGIN_FRAGMENT = 1 << 15
END_FRAGMENT = 1 << 14
UNFRAGMENTED = BEGIN_FRAGMENT | END_FRAGMENT
IS_FINAL = 1 << 13
BEGIN_DATA = 1 << 12
END_DATA = 1 << 11
IS_NULL = 1 << 10
IS_EVENT = 1 << 9

SIZE_OF_FRAME_HEADER = 6
REQUEST_HEADER = 16   # type(4) correlation(8) partition(4)
RESPONSE_HEADER = 13  # type(4) correlation(8) backup-acks(1)

EXCEPTION_MSG_TYPE = 0
INVALID_FENCE = 0

MSG = {
    "client.authentication": 0x000100,
    "cpgroup.createcpgroup": 0x1E0100,
    "cpsession.createsession": 0x1F0100,
    "cpsession.closesession": 0x1F0200,
    "cpsession.heartbeatsession": 0x1F0300,
    "cpsession.generatethreadid": 0x1F0400,
    "atomiclong.addandget": 0x090300,
    "atomiclong.compareandset": 0x090400,
    "atomiclong.get": 0x090500,
    "atomiclong.getandset": 0x090700,
    "fencedlock.lock": 0x070100,
    "fencedlock.trylock": 0x070200,
    "fencedlock.unlock": 0x070300,
    "semaphore.init": 0x0C0100,
    "semaphore.acquire": 0x0C0200,
    "semaphore.release": 0x0C0300,
}


class HzError(Exception):
    """Server-side error response (ErrorCodec). ``code`` is the first
    error holder's numeric code, ``class_name`` its Java class."""

    def __init__(self, code: int, class_name: str, message: str):
        super().__init__(f"{class_name}({code}): {message}")
        self.code = code
        self.class_name = class_name
        self.message = message


class Frame:
    __slots__ = ("flags", "payload")

    def __init__(self, payload: bytes, flags: int = 0):
        self.flags = flags
        self.payload = payload

    def is_null(self) -> bool:
        return bool(self.flags & IS_NULL)

    def is_begin(self) -> bool:
        return bool(self.flags & BEGIN_DATA)

    def is_end(self) -> bool:
        return bool(self.flags & END_DATA)


NULL_FRAME = Frame(b"", IS_NULL)
BEGIN_FRAME = Frame(b"", BEGIN_DATA)
END_FRAME = Frame(b"", END_DATA)


def encode_message(frames: list[Frame]) -> bytes:
    """Serializes frames; first gets UNFRAGMENTED, last gets IS_FINAL."""
    out = bytearray()
    last = len(frames) - 1
    for i, f in enumerate(frames):
        flags = f.flags
        if i == 0:
            flags |= UNFRAGMENTED
        if i == last:
            flags |= IS_FINAL
        out += struct.pack("<IH", len(f.payload) + SIZE_OF_FRAME_HEADER,
                           flags)
        out += f.payload
    return bytes(out)


def read_message(sock: socket.socket) -> list[Frame]:
    """Reads frames until one carries IS_FINAL."""
    frames = []
    while True:
        size, flags = struct.unpack("<IH",
                                    recv_exact(sock, SIZE_OF_FRAME_HEADER))
        payload = recv_exact(sock, size - SIZE_OF_FRAME_HEADER)
        frames.append(Frame(payload, flags))
        if flags & IS_FINAL:
            return frames


# -- codec primitives -------------------------------------------------------

def str_frame(s: str) -> Frame:
    return Frame(s.encode("utf-8"))


def nullable_str_frame(s: str | None) -> Frame:
    return NULL_FRAME if s is None else str_frame(s)


def encode_uuid(u: bytes | None) -> bytes:
    """17-byte nullable UUID: is-null bool + 16 raw bytes."""
    if u is None:
        return b"\x01" + b"\x00" * 16
    assert len(u) == 16
    return b"\x00" + u


def random_uuid() -> bytes:
    return os.urandom(16)


def raft_group_frames(group: "RaftGroupId") -> list[Frame]:
    """RaftGroupId custom codec: BEGIN, fixed [seed(8) id(8)], name,
    END."""
    return [BEGIN_FRAME,
            Frame(struct.pack("<qq", group.seed, group.group_id)),
            str_frame(group.name),
            END_FRAME]


class RaftGroupId:
    __slots__ = ("name", "seed", "group_id")

    def __init__(self, name: str, seed: int, group_id: int):
        self.name = name
        self.seed = seed
        self.group_id = group_id

    def __repr__(self):
        return f"RaftGroupId({self.name!r}, {self.seed}, {self.group_id})"


def decode_raft_group(frames: list[Frame], i: int) -> tuple[RaftGroupId, int]:
    """Decodes the custom type starting at frames[i] (a BEGIN frame);
    returns (group, next index). Skips unknown trailing fields until the
    matching END frame (forward-compatible decode)."""
    assert frames[i].is_begin(), "RaftGroupId must start with BEGIN"
    seed, gid = struct.unpack_from("<qq", frames[i + 1].payload, 0)
    name = frames[i + 2].payload.decode("utf-8")
    depth, j = 1, i + 3
    while depth > 0:
        if frames[j].is_begin():
            depth += 1
        elif frames[j].is_end():
            depth -= 1
        j += 1
    return RaftGroupId(name, seed, gid), j


def decode_error(frames: list[Frame]) -> HzError:
    """ErrorCodec response: a list-of-ErrorHolder data structure; each
    holder = BEGIN, fixed [errorCode(4)], className str, message
    nullable str, stack-trace list, END. Only the first holder's
    essentials are surfaced."""
    try:
        # frames[0] initial; frames[1] list BEGIN; frames[2] holder
        # BEGIN; frames[3] holder initial [errorCode]; then var fields
        code = struct.unpack_from("<i", frames[3].payload, 0)[0]
        class_name = frames[4].payload.decode("utf-8", "replace")
        msg_f = frames[5]
        message = "" if msg_f.is_null() else \
            msg_f.payload.decode("utf-8", "replace")
        return HzError(code, class_name, message)
    except (IndexError, struct.error):
        return HzError(-1, "unknown", "undecodable error response")


# -- the client -------------------------------------------------------------

class HzClient:
    """One TCP connection to a member, authenticated, single in-flight
    invocation (the suite runs one client per logical process, matching
    the generator's thread model — no multiplexing needed)."""

    def __init__(self, host: str, port: int = 5701,
                 cluster_name: str = "jepsen",
                 client_name: str | None = None,
                 timeout_s: float = 10.0):
        self.host = host
        self.port = port
        self.cluster_name = cluster_name
        self.client_name = client_name or f"jepsen-{os.getpid()}"
        self.timeout_s = timeout_s
        self.sock: socket.socket | None = None
        self._correlation = itertools.count(1)
        self._lock = threading.Lock()
        self._groups: dict[str, RaftGroupId] = {}
        self._sessions: dict[tuple[str, int], tuple[int, float, float]] = {}
        self._thread_id: int | None = None

    # -- connection/auth ----------------------------------------------------

    def connect(self) -> "HzClient":
        # a (re)connect is a fresh client to the server: cached groups,
        # CP sessions and the thread id belong to the old connection
        self._groups.clear()
        self._sessions.clear()
        self._thread_id = None
        self.sock = socket.create_connection((self.host, self.port),
                                             timeout=self.timeout_s)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.sendall(PROTOCOL_ID)
        frames = self._invoke(
            MSG["client.authentication"],
            fixed=encode_uuid(random_uuid()) + b"\x01",  # uuid, ser-version
            var=[str_frame(self.cluster_name),
                 NULL_FRAME,                    # username
                 NULL_FRAME,                    # password
                 str_frame("PYT"),              # client type
                 str_frame("5.3"),              # client hz version
                 str_frame(self.client_name),
                 BEGIN_FRAME, END_FRAME])       # labels: empty list
        status = frames[0].payload[RESPONSE_HEADER]
        if status != 0:
            raise HzError(status, "AuthenticationException",
                          f"status {status}")
        return self

    def close(self):
        close_quietly(self.sock)
        self.sock = None

    # -- invocation ---------------------------------------------------------

    def _invoke(self, msg_type: int, fixed: bytes = b"",
                var: list[Frame] | None = None,
                partition: int = -1) -> list[Frame]:
        """Sends one request, returns the matching response's frames.
        Events (unsolicited pushes) are skipped; an error response
        raises HzError."""
        if self.sock is None:
            raise ConnectionError("not connected")
        corr = next(self._correlation)
        initial = Frame(struct.pack("<IqI", msg_type, corr,
                                    partition & 0xFFFFFFFF) + fixed)
        msg = encode_message([initial] + (var or []))
        with self._lock:
            self.sock.sendall(msg)
            while True:
                frames = read_message(self.sock)
                if frames[0].flags & IS_EVENT:
                    continue
                rtype, rcorr = struct.unpack_from("<Iq",
                                                  frames[0].payload, 0)
                if rcorr != corr:
                    continue  # stale response from an abandoned retry
                if rtype == EXCEPTION_MSG_TYPE:
                    raise decode_error(frames)
                return frames

    @staticmethod
    def _fixed(frames: list[Frame], fmt: str):
        vals = struct.unpack_from(fmt, frames[0].payload, RESPONSE_HEADER)
        return vals[0] if len(vals) == 1 else vals

    # -- CP plumbing --------------------------------------------------------

    def cp_group(self, proxy_name: str = "default") -> RaftGroupId:
        """Resolves (and caches) the Raft group for a CP proxy name
        ("name@group", default group otherwise)."""
        group_name = proxy_name.split("@", 1)[1] if "@" in proxy_name \
            else "default"
        g = self._groups.get(group_name)
        if g is None:
            frames = self._invoke(MSG["cpgroup.createcpgroup"],
                                  var=[str_frame(group_name)])
            g, _ = decode_raft_group(frames, 1)
            self._groups[group_name] = g
        return g

    def thread_id(self, group: RaftGroupId) -> int:
        if self._thread_id is None:
            frames = self._invoke(MSG["cpsession.generatethreadid"],
                                  var=raft_group_frames(group))
            self._thread_id = self._fixed(frames, "<q")
        return self._thread_id

    def session_id(self, group: RaftGroupId) -> int:
        """Current CP session for the group, creating or refreshing as
        needed (the Java client's background heartbeater, done lazily:
        a heartbeat rides ahead of any op once half the TTL elapsed)."""
        key = (group.name, group.group_id)
        now = time.monotonic()
        entry = self._sessions.get(key)
        if entry is not None:
            sid, ttl_s, last = entry
            if now - last < ttl_s / 2:
                return sid
            try:
                self._invoke(MSG["cpsession.heartbeatsession"],
                             fixed=struct.pack("<q", sid),
                             var=raft_group_frames(group))
                self._sessions[key] = (sid, ttl_s, now)
                return sid
            except HzError:
                del self._sessions[key]  # expired: fall through, recreate
        frames = self._invoke(MSG["cpsession.createsession"],
                              var=raft_group_frames(group)
                              + [str_frame(self.client_name)])
        sid, ttl_ms, _hb = self._fixed(frames, "<qqq")
        self._sessions[key] = (sid, max(ttl_ms / 1000.0, 1.0), now)
        return sid

    def close_session(self, group: RaftGroupId):
        key = (group.name, group.group_id)
        entry = self._sessions.pop(key, None)
        if entry is not None:
            self._invoke(MSG["cpsession.closesession"],
                         fixed=struct.pack("<q", entry[0]),
                         var=raft_group_frames(group))

    # -- AtomicLong ---------------------------------------------------------

    def atomic_add_and_get(self, name: str, delta: int) -> int:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.addandget"],
                              fixed=struct.pack("<q", delta),
                              var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    def atomic_get(self, name: str) -> int:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.get"],
                              var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    def atomic_compare_and_set(self, name: str, expected: int,
                               updated: int) -> bool:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.compareandset"],
                              fixed=struct.pack("<qq", expected, updated),
                              var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    def atomic_get_and_set(self, name: str, value: int) -> int:
        g = self.cp_group(name)
        frames = self._invoke(MSG["atomiclong.getandset"],
                              fixed=struct.pack("<q", value),
                              var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    # -- FencedLock ---------------------------------------------------------

    def lock_try_lock(self, name: str, timeout_ms: int = 5000) -> int:
        """tryLock: the fencing token, or INVALID_FENCE (0) when the
        wait timed out."""
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["fencedlock.trylock"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid())
            + struct.pack("<q", timeout_ms),
            var=raft_group_frames(g) + [str_frame(name)])
        return self._fixed(frames, "<q")

    def lock_unlock(self, name: str) -> bool:
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["fencedlock.unlock"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid()),
            var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    # -- Semaphore ----------------------------------------------------------

    def semaphore_init(self, name: str, permits: int) -> bool:
        g = self.cp_group(name)
        frames = self._invoke(MSG["semaphore.init"],
                              fixed=struct.pack("<i", permits),
                              var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    def semaphore_acquire(self, name: str, permits: int = 1,
                          timeout_ms: int = 5000) -> bool:
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["semaphore.acquire"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid())
            + struct.pack("<iq", permits, timeout_ms),
            var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b"))

    def semaphore_release(self, name: str, permits: int = 1) -> bool:
        g = self.cp_group(name)
        sid = self.session_id(g)
        tid = self.thread_id(g)
        frames = self._invoke(
            MSG["semaphore.release"],
            fixed=struct.pack("<qq", sid, tid) + encode_uuid(random_uuid())
            + struct.pack("<i", permits),
            var=raft_group_frames(g) + [str_frame(name)])
        return bool(self._fixed(frames, "<b")) if \
            len(frames[0].payload) > RESPONSE_HEADER else True
