"""Shared socket plumbing for the wire-protocol suite clients
(``_mysql.py``, ``_postgres.py``, ``_resp.py``, ``_amqp.py``,
``_reql.py``, ``_aerospike.py``): exact reads that refuse to return
short data, and quiet closes."""
from __future__ import annotations

import socket


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Reads exactly n bytes or raises ConnectionError — a short read
    must never surface as a (truncated) protocol unit."""
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("connection closed")
        out += chunk
    return out


def close_quietly(sock: socket.socket | None) -> None:
    if sock is None:
        return
    try:
        sock.close()
    except OSError:
        pass
