"""YugabyteDB test suite (reference: yugabyte/src/yugabyte/ — the
reference's workloads-as-data flagship: a registry of YCQL and YSQL
workloads with a ``workload-options-expected-to-pass`` sweep for
test-all, yugabyte/core.clj:74-123).

Here the YSQL side rides the shared Postgres-wire client on port 5433
(YSQL speaks the postgres protocol): set, bank (negative balances
allowed, matching ``workload-allow-neg``), long-fork, append, register,
wr, counter, single/multi-key-acid and default-value. The YCQL side
(``--api ycql``) rides the from-scratch CQL native-protocol client
(suites/_cql_client.py) on port 9042: counter, set, set-index, bank,
long-fork, single-key-acid and multi-key-acid, with transactional
workloads issued as single-statement ``BEGIN TRANSACTION`` batches the
way the reference's ycql clients build them.

DB automation per yugabyte/auto.clj: a release tarball, yb-master on
the first (up to) three nodes with the full master address list,
yb-tserver everywhere.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod, fakes
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn,
                               workload_registry)
from jepsen_tpu.suites._pg_client import PGSuiteClient

logger = logging.getLogger("jepsen.yugabyte")

DEFAULT_VERSION = "2.18.5.0"
DIR = "/opt/yugabyte"
MASTER_RPC_PORT = 7100
TSERVER_RPC_PORT = 9100
YSQL_PORT = 5433
YCQL_PORT = 9042  # yb-tserver's CQL proxy (on by default)
DB_NAME = "jepsen"
DB_USER = "yugabyte"
DB_PASS = "yugabyte"
MASTER_COUNT = 3

# reference registry shape (yugabyte/core.clj:74-104)
YSQL_WORKLOADS = ("append", "append-table", "set", "bank", "long-fork",
                  "register", "wr", "counter", "single-key-acid",
                  "multi-key-acid", "default-value")
YCQL_WORKLOADS = ("counter", "set", "set-index", "bank", "long-fork",
                  "single-key-acid", "multi-key-acid")


def tarball_url(version: str) -> str:
    return (f"https://downloads.yugabyte.com/releases/{version}/"
            f"yugabyte-{version}-b0-linux-x86_64.tar.gz")


def master_nodes(test: dict) -> list[str]:
    """The first three nodes carry masters (yugabyte/auto.clj:57-67)."""
    return (test.get("nodes") or [])[:MASTER_COUNT]


def master_addresses(test: dict) -> str:
    """``n1:7100,n2:7100,n3:7100`` (yugabyte/auto.clj:74-79)."""
    return ",".join(f"{n}:{MASTER_RPC_PORT}" for n in master_nodes(test))


def workloads_expected_to_pass() -> dict:
    """name → workload constructor, the test-all sweep surface
    (yugabyte/core.clj:110-123 workload-options-expected-to-pass).
    append-table rides the append kit — the client's txn_style routes
    its micro-ops to per-key tables (ysql/append_table.clj)."""
    reg = workload_registry()
    return {name: (reg["append"] if name == "append-table" else reg[name])
            for name in YSQL_WORKLOADS}


def ycql_workload(name: str, base: dict, accelerator: str = "auto") -> dict:
    """YCQL workload kit (yugabyte/core.clj:74-85): the shared kits plus
    the set-index variant (ycql/set.clj CQLSetIndexClient — adds are
    transactional rows with a group column, reads go through the
    secondary index per group; the kit is the set kit with a test-map
    marker the YCQL client dispatches on)."""
    from jepsen_tpu.suites import workload_registry

    reg = workload_registry()
    if name == "set-index":
        w = reg["set"](base, accelerator=accelerator)
        return {**w, "set-index": True}
    if name not in YCQL_WORKLOADS:
        raise ValueError(f"not a YCQL workload: {name!r}")
    return reg[name](base, accelerator=accelerator)


class YugabyteDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.Primary,
                 db_mod.LogFiles):
    """Master/tserver lifecycle (yugabyte/auto.clj): masters on the
    first three nodes (barrier), tservers everywhere."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        from jepsen_tpu import core
        if not cu.file_exists(f"{DIR}/bin/yb-master"):
            logger.info("%s: installing yugabyte %s", node, self.version)
            cu.install_archive(tarball_url(self.version), DIR)
            control.exec_(control.lit(
                f"{DIR}/bin/post_install.sh >/dev/null 2>&1 || true"))
        self.start_master(test, node)
        core.synchronize(test, timeout_s=600.0)
        self.start_tserver(test, node)
        cu.await_tcp_port(YSQL_PORT, host=node, timeout_s=300.0)
        core.synchronize(test, timeout_s=600.0)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            control.exec_(control.lit(
                f"{DIR}/bin/ysqlsh -h {node} -p {YSQL_PORT} -U {DB_USER} "
                f"-c 'CREATE DATABASE {DB_NAME}' 2>/dev/null || true"))
        core.synchronize(test, timeout_s=600.0)

    def start_master(self, test, node):
        """yb-master with the full master list (yugabyte/auto.clj:84-90)."""
        if node not in master_nodes(test):
            return False
        cu.mkdir(f"{DIR}/master")
        return cu.start_daemon(
            {"logfile": f"{DIR}/master/stdout",
             "pidfile": f"{DIR}/master.pid", "chdir": DIR},
            f"{DIR}/bin/yb-master",
            "--master_addresses", master_addresses(test),
            "--rpc_bind_addresses", f"{node}:{MASTER_RPC_PORT}",
            "--fs_data_dirs", f"{DIR}/master",
            "--replication_factor", str(len(master_nodes(test))))

    def start_tserver(self, test, node):
        cu.mkdir(f"{DIR}/tserver")
        return cu.start_daemon(
            {"logfile": f"{DIR}/tserver/stdout",
             "pidfile": f"{DIR}/tserver.pid", "chdir": DIR},
            f"{DIR}/bin/yb-tserver",
            "--tserver_master_addrs", master_addresses(test),
            "--rpc_bind_addresses", f"{node}:{TSERVER_RPC_PORT}",
            "--fs_data_dirs", f"{DIR}/tserver",
            "--enable_ysql",
            "--pgsql_proxy_bind_address", f"0.0.0.0:{YSQL_PORT}")

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/master")
        cu.rm_rf(f"{DIR}/tserver")

    def start(self, test, node):
        self.start_master(test, node)
        self.start_tserver(test, node)

    def kill(self, test, node):
        for name in ("yb-tserver", "yb-master"):
            cu.grepkill(name)

    def pause(self, test, node):
        for name in ("yb-tserver", "yb-master"):
            cu.grepkill(name, sig="STOP")

    def resume(self, test, node):
        for name in ("yb-tserver", "yb-master"):
            cu.grepkill(name, sig="CONT")

    # ---- role-targeted process surface (yugabyte/nemesis.clj:12-44;
    # the RoleProcess nemesis drives one role at a time) ----------------
    def role_nodes(self, test, role):
        return (master_nodes(test) if role == "master"
                else list(test.get("nodes") or []))

    def kill_master(self, test, node):
        cu.grepkill("yb-master")

    def kill_tserver(self, test, node):
        cu.grepkill("yb-tserver")

    def stop_master(self, test, node):
        cu.grepkill("yb-master", sig="TERM")

    def stop_tserver(self, test, node):
        cu.grepkill("yb-tserver", sig="TERM")

    def pause_master(self, test, node):
        cu.grepkill("yb-master", sig="STOP")

    def pause_tserver(self, test, node):
        cu.grepkill("yb-tserver", sig="STOP")

    def resume_master(self, test, node):
        cu.grepkill("yb-master", sig="CONT")

    def resume_tserver(self, test, node):
        cu.grepkill("yb-tserver", sig="CONT")

    def primaries(self, test):
        return master_nodes(test)

    def setup_primary(self, test, node):
        pass

    def log_files(self, test, node):
        return [f"{DIR}/master/stdout", f"{DIR}/tserver/stdout"]


SUPPORTED_WORKLOADS = YSQL_WORKLOADS

# role-targeted process faults (yugabyte/nemesis.clj:12-44) ride --fault
YUGABYTE_FAULTS = ("kill-master", "kill-tserver", "stop-master",
                   "stop-tserver", "pause-master", "pause-tserver")


class FakeYugabyte(fakes.KVStore):
    """Fake-mode double with the master/tserver role surface: role verbs
    meta-log so tests can assert the fault vocabulary reaches the right
    roles (masters = first three nodes, like the real topology)."""

    def role_nodes(self, test, role):
        return (master_nodes(test) if role == "master"
                else list(test.get("nodes") or []))

    def _role_note(self, verb, role, node):
        self._note(f"db-{verb}-{role}", node)

    def kill_master(self, test, node):
        self._role_note("kill", "master", node)

    def kill_tserver(self, test, node):
        self._role_note("kill", "tserver", node)

    def stop_master(self, test, node):
        self._role_note("stop", "master", node)

    def stop_tserver(self, test, node):
        self._role_note("stop", "tserver", node)

    def pause_master(self, test, node):
        self._role_note("pause", "master", node)

    def pause_tserver(self, test, node):
        self._role_note("pause", "tserver", node)

    def resume_master(self, test, node):
        self._role_note("resume", "master", node)

    def resume_tserver(self, test, node):
        self._role_note("resume", "tserver", node)

    def start_master(self, test, node):
        self._role_note("start", "master", node)

    def start_tserver(self, test, node):
        self._role_note("start", "tserver", node)


def yugabyte_test(opts_dict: dict | None = None) -> dict:
    """--api picks the reference's workload/client split
    (yugabyte/core.clj:74-118): ysql rides the shared Postgres-wire
    client on 5433, ycql the CQL-wire client on 9042."""
    from jepsen_tpu.nemesis.db_specific import yugabyte_fault_packages
    o = dict(opts_dict or {})
    api = o.get("api", "ysql")
    workload = o.get("workload") or SUPPORTED_WORKLOADS[0]

    def make_real(o):
        db = YugabyteDB(o.get("version", DEFAULT_VERSION))
        if api == "ycql":
            from jepsen_tpu.suites._cql_client import YCQLSuiteClient
            client = YCQLSuiteClient(port=YCQL_PORT)
        else:
            client = PGSuiteClient(
                port=YSQL_PORT, database=DB_NAME, user=DB_USER,
                password=DB_PASS,
                isolation=o.get("isolation", "serializable"),
                txn_style="wr" if workload in ("wr", "long-fork")
                else workload if workload == "append-table"
                else "append")
        return {"db": db, "client": client, "os": Debian()}

    kw = {}
    if api == "ycql":
        kw["make_workload"] = lambda name, base: ycql_workload(
            name, base, accelerator=base["accelerator"])
    else:
        # append-table is the Elle list-append kit routed to per-key
        # tables by the client (ysql/append_table.clj); checker-side it
        # IS the append workload
        from jepsen_tpu.suites import workload_registry
        kw["extra_workloads"] = {
            "append-table": lambda base: workload_registry()["append"](
                base, accelerator=base.get("accelerator", "auto"))}
    return build_suite_test(
        o, db_name="yugabyte",
        supported_workloads=(YCQL_WORKLOADS if api == "ycql"
                             else SUPPORTED_WORKLOADS),
        fault_packages=yugabyte_fault_packages(),
        fake_db=FakeYugabyte,
        make_real=make_real, **kw)


# the sweep over workloads expected to pass (yugabyte/core.clj:110-123)
# rides the shared runner
main_all = standard_test_all(yugabyte_test,
                             tuple(workloads_expected_to_pass()),
                             name="jepsen-yugabyte")

main = cli.single_test_cmd(
    standard_test_fn(yugabyte_test, extra_keys=("isolation", "version",
                                                "api")),
    standard_opt_fn(tuple(dict.fromkeys(SUPPORTED_WORKLOADS
                                        + YCQL_WORKLOADS)),
                    workload_default=None,  # per-api default (see test fn)
                    extra=lambda p: (
                        p.add_argument("--api", default="ysql",
                                       choices=["ysql", "ycql"]),
                        p.add_argument("--isolation", default="serializable",
                                       choices=["read-committed",
                                                "repeatable-read",
                                                "serializable"]),
                        p.add_argument("--version",
                                       default=DEFAULT_VERSION)),
                    extra_faults=YUGABYTE_FAULTS),
    name="jepsen-yugabyte")


if __name__ == "__main__":
    import sys
    sys.exit(main())
