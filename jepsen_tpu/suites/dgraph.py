"""Dgraph test suite (reference: dgraph/ in jaydenwen123/jepsen — the
largest reference suite: dgraph/src/jepsen/dgraph/{set,bank,delete,
long_fork,upsert,sequential}.clj over a zero+alpha cluster,
dgraph/src/jepsen/dgraph/support.clj for DB automation).

The client rides Dgraph's HTTP API. Set adds are single JSON mutations
with ``commitNow``; register writes and CAS are **upsert blocks** — a
DQL query binding the key's uid/value plus a conditional mutation
(``@if``), executed atomically server-side, the HTTP equivalent of the
reference upsert.clj's transactional upserts. Reads query by indexed
key predicate.

DB automation installs the dgraph binary, runs ``dgraph zero`` on the
first node (``--replicas N`` for one raft group) and ``dgraph alpha``
on every node pointing at it — support.clj's zero/alpha bring-up.

Dgraph-specific probes: ``delete`` (index freshness, delete.clj) and
``sequential`` (per-process monotonic register, sequential.clj) beyond
the shared kits, plus ``--fault move-tablet`` — the tablet-mover
nemesis shuffling predicates between groups through zero's admin API
(nemesis.clj:51-99).
"""
from __future__ import annotations

import logging
import urllib.error
import urllib.parse

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu import nemesis as nemesis_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_json

logger = logging.getLogger("jepsen.dgraph")

DEFAULT_VERSION = "23.1.1"
DIR = "/opt/dgraph"
ZERO_LOG = f"{DIR}/zero.log"
ALPHA_LOG = f"{DIR}/alpha.log"
ZERO_PID = f"{DIR}/zero.pid"
ALPHA_PID = f"{DIR}/alpha.pid"
ALPHA_HTTP_PORT = 8080
ZERO_GRPC_PORT = 5080

# @upsert on the indexed predicates makes dgraph conflict-check the
# index reads inside upsert blocks — without it two conditional creates
# of one key can both commit and the client fabricates duplicates the
# checkers would blame on the DB (the reference schemas carry the same
# directive)
SCHEMA = ("key: int @index(int) @upsert .\nval: int .\n"
          "value: int @index(int) .\n"
          "el: int @index(int) .\n"
          "acct: int @index(int) @upsert .\nbalance: int .\n"
          "ukey: int @index(int) @upsert .\nuval: int .\n")


def binary_url(version: str) -> str:
    return (f"https://github.com/dgraph-io/dgraph/releases/download/"
            f"v{version}/dgraph-linux-amd64.tar.gz")


class DgraphDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing dgraph %s", node, self.version)
        cu.install_archive(binary_url(self.version), DIR)
        nodes = test.get("nodes") or []
        zero_node = nodes[0] if nodes else node
        if node == zero_node:
            cu.start_daemon(
                {"logfile": ZERO_LOG, "pidfile": ZERO_PID, "chdir": DIR},
                f"{DIR}/dgraph", "zero", "--my", f"{node}:{ZERO_GRPC_PORT}",
                "--replicas", str(len(nodes) or 1))
            cu.await_tcp_port(ZERO_GRPC_PORT, host=zero_node)
        self.start(test, node)
        cu.await_tcp_port(ALPHA_HTTP_PORT, host=node)
        if node == zero_node:
            http_json(f"http://{node}:{ALPHA_HTTP_PORT}/alter",
                      raw_body=SCHEMA.encode(), timeout_s=30)

    def teardown(self, test, node):
        self.kill(test, node)
        for d in ("p", "w", "zw"):
            cu.rm_rf(f"{DIR}/{d}")

    def start(self, test, node):
        nodes = test.get("nodes") or []
        zero_node = nodes[0] if nodes else node
        return cu.start_daemon(
            {"logfile": ALPHA_LOG, "pidfile": ALPHA_PID, "chdir": DIR},
            f"{DIR}/dgraph", "alpha", "--my", f"{node}:7080",
            "--zero", f"{zero_node}:{ZERO_GRPC_PORT}",
            "--security", "whitelist=0.0.0.0/0")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/dgraph", ALPHA_PID)
        cu.stop_daemon(f"{DIR}/dgraph", ZERO_PID)
        cu.grepkill("dgraph")

    def pause(self, test, node):
        cu.grepkill("dgraph", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("dgraph", sig="CONT")

    def log_files(self, test, node):
        return [ZERO_LOG, ALPHA_LOG]


class DgraphClient(Client):
    """Register/set ops via HTTP upsert blocks and DQL queries."""

    def __init__(self, timeout_s: float = 10.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return type(self)(self.timeout_s, node)

    def setup(self, test):
        # bank accounts: conditional create per account — idempotent
        # across clients (dgraph/bank.clj seeds the same way)
        for a in test.get("accounts", []):
            self._mutate({
                "query": "{ q(func: eq(acct, %d)) { u as uid } }" % int(a),
                "cond": "@if(eq(len(u), 0))",
                "set": [{"acct": int(a), "balance": 10}]})

    def _mutate(self, body: dict):
        doc = http_json(
            f"http://{self.node}:{ALPHA_HTTP_PORT}/mutate?commitNow=true",
            body, timeout_s=self.timeout_s)
        errs = doc.get("errors")
        if errs:
            raise DgraphError(str(errs))
        return doc

    def _query(self, q: str):
        doc = http_json(f"http://{self.node}:{ALPHA_HTTP_PORT}/query",
                        raw_body=q.encode(),
                        headers={"Content-Type": "application/dql"},
                        timeout_s=self.timeout_s)
        errs = doc.get("errors")
        if errs:
            raise DgraphError(str(errs))
        return doc.get("data") or {}

    def _read_register(self, k):
        data = self._query(
            "{ q(func: eq(key, %d)) { val } }" % k)
        rows = data.get("q") or []
        return rows[0].get("val") if rows else None

    # -- real dgraph transactions: snapshot query at start_ts, mutations
    # -- at the same ts, then commit with the server's conflict keys —
    # -- the reference client's txn shape (dgraph/client.clj with-txn)
    def _txn_query(self, dql: str):
        doc = http_json(f"http://{self.node}:{ALPHA_HTTP_PORT}/query",
                        raw_body=dql.encode(),
                        headers={"Content-Type": "application/dql"},
                        timeout_s=self.timeout_s)
        if doc.get("errors"):
            raise DgraphError(str(doc["errors"]))
        ts = (doc.get("extensions") or {}).get("txn", {}).get("start_ts")
        return doc.get("data") or {}, ts

    def _txn_mutate(self, start_ts, body: dict):
        mut = http_json(
            f"http://{self.node}:{ALPHA_HTTP_PORT}/mutate"
            f"?startTs={start_ts}", body, timeout_s=self.timeout_s)
        if mut.get("errors"):
            raise DgraphError(str(mut["errors"]))
        return (mut.get("extensions") or {}).get("txn", {})

    def _txn_commit(self, start_ts, txn: dict):
        try:
            commit = http_json(
                f"http://{self.node}:{ALPHA_HTTP_PORT}/commit"
                f"?startTs={start_ts}",
                {"keys": txn.get("keys") or [],
                 "preds": txn.get("preds") or []},
                timeout_s=self.timeout_s)
        except urllib.error.HTTPError as e:
            if e.code == 409:  # aborted: lost the conflict race
                raise DgraphAborted("commit aborted")
            raise
        if commit.get("errors"):
            raise DgraphError(str(commit["errors"]))

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if test.get("delete-workload"):
                return self._delete_invoke(op)
            if test.get("dgraph-sequential"):
                return self._seq_register_invoke(op)
            if f == "add":
                self._mutate({"set": [{"el": v}]})
                return {**op, "type": "ok"}
            if f == "read" and v is None and test.get("accounts"):
                data = self._query(
                    "{ q(func: has(acct)) { acct balance } }")
                return {**op, "type": "ok",
                        "value": {int(r["acct"]): int(r.get("balance", 0))
                                  for r in (data.get("q") or [])}}
            if f == "read" and v is None:
                data = self._query("{ q(func: has(el)) { el } }")
                elems = sorted(row["el"] for row in (data.get("q") or []))
                return {**op, "type": "ok", "value": elems}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self._read_register(k)]}
            if f == "write":
                k, val = v
                # upsert: bind the key's uid, write through it (or create)
                self._mutate({
                    "query": "{ q(func: eq(key, %d)) { u as uid } }" % k,
                    "set": [{"uid": "uid(u)", "key": k, "val": val}]})
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                data, ts = self._txn_query(
                    "{ q(func: eq(key, %d)) { uid val } }" % k)
                rows = data.get("q") or []
                if not rows or rows[0].get("val") != old or not ts:
                    return {**op, "type": "fail"}
                txn = self._txn_mutate(
                    ts, {"set": [{"uid": rows[0]["uid"], "val": new}]})
                self._txn_commit(ts, txn)
                return {**op, "type": "ok"}
            if f == "transfer":
                return self._transfer(op)
            if f == "txn":
                return self._wr_txn(op)
            if f == "upsert":
                return self._upsert(op)
            if f == "read-uids":
                k, _ = v
                data = self._query(
                    "{ q(func: eq(ukey, %d)) { uid } }" % int(k))
                uids = [r["uid"] for r in (data.get("q") or [])]
                return {**op, "type": "ok", "value": [k, uids]}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except DgraphAborted:
            return {**op, "type": "fail", "error": ["txn", "aborted"]}
        except DgraphError as e:
            # txn conflicts abort server-side: definite failure
            if "conflict" in str(e).lower() or "aborted" in str(e).lower():
                return {**op, "type": "fail", "error": ["txn", str(e)]}
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["dgraph", str(e)]}
        except urllib.error.HTTPError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def _transfer(self, op):
        """Two-account transfer in one dgraph txn (dgraph/bank.clj):
        snapshot both balances, refuse overdrafts, mutate both at the
        same start_ts, commit with conflict keys."""
        t = op.get("value") or {}
        frm, to = int(t.get("from")), int(t.get("to"))
        amount = int(t.get("amount", 0))
        data, ts = self._txn_query(
            "{ a(func: eq(acct, %d)) { uid balance } "
            "b(func: eq(acct, %d)) { uid balance } }" % (frm, to))
        a = (data.get("a") or [None])[0]
        b = (data.get("b") or [None])[0]
        if not a or not b or not ts:
            return {**op, "type": "fail", "error": ["no-such-account"]}
        if int(a.get("balance", 0)) - amount < 0:
            return {**op, "type": "fail",
                    "error": ["negative", frm,
                              int(a.get("balance", 0)) - amount]}
        txn = self._txn_mutate(ts, {"set": [
            {"uid": a["uid"], "balance": int(a.get("balance", 0)) - amount},
            {"uid": b["uid"], "balance": int(b.get("balance", 0)) + amount},
        ]})
        self._txn_commit(ts, txn)
        return {**op, "type": "ok"}

    def _wr_txn(self, op):
        """rw-register txn (dgraph/wr.clj, long_fork.clj): every key's
        row binds in one snapshot query; reads fill from it, writes go
        through ONE upsert-block mutation at the same start_ts — each
        write binds its key's uid with a query var, so an existing row
        updates in place and a fresh key creates exactly once (two
        concurrent first-writers conflict on the @upsert index read
        instead of both creating) — then commit."""
        mops = op.get("value") or []
        keys = sorted({int(k) for _, k, _ in mops})
        blocks = " ".join(
            "k%d(func: eq(key, %d)) { uid val }" % (k, k) for k in keys)
        data, ts = self._txn_query("{ %s }" % blocks)
        row = {k: (data.get("k%d" % k) or [None])[0] for k in keys}
        out = []
        last_write: dict = {}
        for fm, k, val in mops:
            k = int(k)
            if fm == "r":
                r = row.get(k)
                out.append(["r", k, r.get("val") if r else None])
            else:
                last_write[k] = int(val)  # register: last write wins
                row[k] = {"val": int(val)}  # later reads in-txn observe it
                out.append(["w", k, int(val)])
        if last_write:
            if not ts:
                raise DgraphError("no start_ts for txn")
            wkeys = sorted(last_write)
            bind = " ".join(
                "w%d(func: eq(key, %d)) { u%d as uid }" % (k, k, k)
                for k in wkeys)
            txn = self._txn_mutate(ts, {
                "query": "{ %s }" % bind,
                "set": [{"uid": "uid(u%d)" % k, "key": k,
                         "val": last_write[k]} for k in wkeys]})
            self._txn_commit(ts, txn)
        return {**op, "type": "ok", "value": out}

    def _upsert(self, op):
        """Conditional create (dgraph/upsert.clj): one upsert block
        whose mutation is gated on the key being absent — two racers
        both seeing absent and both creating is the duplicate-upsert
        anomaly the checker hunts."""
        k, uid = op.get("value")
        self._mutate({
            "query": "{ q(func: eq(ukey, %d)) { u as uid } }" % int(k),
            "cond": "@if(eq(len(u), 0))",
            "set": [{"ukey": int(k), "uval": int(uid)}]})
        return {**op, "type": "ok"}

    # -- delete workload (dgraph/delete.clj:32-58) -----------------------

    def _delete_invoke(self, op):
        f = op.get("f")
        k, _ = op.get("value")
        k = int(k)
        if f == "read":
            data = self._query(
                "{ q(func: eq(key, %d)) { uid key } }" % k)
            return {**op, "type": "ok",
                    "value": [k, data.get("q") or []]}
        if f == "upsert":
            doc = self._mutate({
                "query": "{ q(func: eq(key, %d)) { u as uid } }" % k,
                "cond": "@if(eq(len(u), 0))",
                "set": [{"key": k}]})
            created = (doc.get("data") or {}).get("uids") or {}
            if created:
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": ["present"]}
        if f == "delete":
            data, ts = self._txn_query(
                "{ q(func: eq(key, %d)) { uid } }" % k)
            rows = data.get("q") or []
            if not rows or not ts:
                return {**op, "type": "fail", "error": ["not-found"]}
            txn = self._txn_mutate(
                ts, {"delete": [{"uid": rows[0]["uid"]}]})
            self._txn_commit(ts, txn)
            return {**op, "type": "ok"}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    # -- sequential workload (dgraph/sequential.clj:77-100) --------------

    def _seq_register_invoke(self, op):
        f = op.get("f")
        k, _ = op.get("value")
        k = int(k)
        if f == "read":
            data = self._query(
                "{ q(func: eq(key, %d)) { value } }" % k)
            rows = data.get("q") or []
            val = rows[0].get("value", 0) if rows else 0
            return {**op, "type": "ok", "value": [k, int(val or 0)]}
        if f == "inc":
            data, ts = self._txn_query(
                "{ q(func: eq(key, %d)) { uid value } }" % k)
            rows = data.get("q") or []
            if not ts:
                return {**op, "type": "fail", "error": ["no-start-ts"]}
            value = int((rows[0].get("value") if rows else 0) or 0) + 1
            if rows:
                body = {"set": [{"uid": rows[0]["uid"], "value": value}]}
            else:
                body = {"set": [{"key": k, "value": value}]}
            txn = self._txn_mutate(ts, body)
            self._txn_commit(ts, txn)
            return {**op, "type": "ok", "value": [k, value]}
        return {**op, "type": "fail", "error": ["unknown-f", f]}

    def close(self, test):
        pass


class DgraphError(Exception):
    pass


class DgraphAborted(DgraphError):
    """Server-side txn abort (commit 409): a definite failure."""


# ---------------------------------------------------------------------------
# Tablet-mover nemesis (dgraph/nemesis.clj:51-99): shuffles predicate
# tablets between groups through zero's admin HTTP API
# ---------------------------------------------------------------------------

ZERO_HTTP_PORT = 6080


def zero_state(node: str, timeout_s: float = 5.0):
    """Zero's ``/state`` — group/tablet/leader topology, or "timeout"
    when zero doesn't answer (support.clj:159-170)."""
    try:
        return http_json(f"http://{node}:{ZERO_HTTP_PORT}/state",
                         timeout_s=timeout_s)
    except (urllib.error.HTTPError, *NET_ERRORS):
        return "timeout"


def zero_leader(state) -> str | None:
    """The zero leader's node name from a ``/state`` body
    (support.clj:172-181)."""
    for z in (state.get("zeros") or {}).values():
        if z.get("leader"):
            addr = z.get("addr") or ""
            return addr.split(":")[0] or None
    return None


class TabletMover(nemesis_mod.Nemesis):
    """On each op, asks the zero leader to move randomly chosen tablets
    to randomly chosen other groups (dgraph/nemesis.clj:51-99). The op
    value maps each predicate to its [from, to] group pair; reserved
    predicates and not-leader rejections are recorded, not raised."""

    def __init__(self, rng=None):
        import random as _random
        self.rng = rng or _random.Random()

    def fs(self):
        return {"move-tablet"}

    def invoke(self, test, op):
        nodes = list(test.get("nodes") or [])
        state = zero_state(self.rng.choice(nodes)) if nodes else "timeout"
        if state == "timeout" or not isinstance(state, dict):
            return {**op, "type": "info", "value": "timeout"}
        groups = sorted((state.get("groups") or {}).keys())
        leader = zero_leader(state) or (nodes[0] if nodes else None)
        tablets = []
        for group_id, group in sorted((state.get("groups") or {}).items()):
            for pred in sorted((group.get("tablets") or {})):
                tablets.append((pred, str(group_id)))
        self.rng.shuffle(tablets)
        moves = {}
        for pred, group in tablets:
            group2 = self.rng.choice(groups) if groups else group
            if str(group2) == str(group):
                continue
            try:
                http_json(
                    f"http://{leader}:{ZERO_HTTP_PORT}/moveTablet"
                    f"?tablet={urllib.parse.quote(pred)}&group={group2}",
                    timeout_s=20.0)
                moves[pred] = [group, str(group2)]
            except urllib.error.HTTPError as e:
                try:  # zero's refusals are plain text, not JSON
                    body = e.read().decode(errors="replace")
                except OSError:
                    body = ""
                # reserved predicates / stale leaders: expected refusals
                # (nemesis.clj:84-95) — recorded distinguishably from
                # completed moves so history consumers aren't misled
                if "Unable to move reserved" in body \
                        or "not leader" in body.lower():
                    moves[pred] = ["refused", group, str(group2)]
                else:
                    raise
            except NET_ERRORS:
                moves[pred] = ["error", "net"]
        return {**op, "type": "info", "value": moves}


def tablet_mover_package(opts: dict) -> dict:
    """--fault move-tablet: periodic tablet shuffles."""
    from jepsen_tpu import generator as gen
    interval = opts.get("interval", 10.0)
    return {
        "nemesis": TabletMover(),
        "generator": gen.stagger(interval, gen.repeat(
            {"type": "info", "f": "move-tablet", "value": None})),
        "final_generator": None,
        "perf": {"name": "move-tablet", "fs": {"move-tablet"},
                 "start": set(), "stop": set()},
    }


SUPPORTED_WORKLOADS = ("set", "register", "bank", "wr", "long-fork",
                       "upsert", "delete", "sequential")


def _extra_workloads() -> dict:
    """Dgraph's own delete (index freshness, dgraph/delete.clj) and
    sequential (per-process monotonic register, dgraph/sequential.clj —
    NOT the cockroach subkey kit) probes."""
    from jepsen_tpu.workloads import delete_workload, dgraph_sequential
    return {"delete": delete_workload.workload,
            "sequential": dgraph_sequential.workload}


def dgraph_test(opts_dict: dict | None = None) -> dict:
    o = dict(opts_dict or {})
    t = build_suite_test(
        o, db_name="dgraph", supported_workloads=SUPPORTED_WORKLOADS,
        extra_workloads=_extra_workloads(),
        fault_packages={"move-tablet": tablet_mover_package},
        make_real=lambda o: {
            "db": DgraphDB(o.get("version", DEFAULT_VERSION)),
            "client": DgraphClient(), "os": Debian()})
    # --trace (the dgraph/trace.clj opencensus analog) now rides the
    # shared telemetry wiring: build_suite_test carries o["trace"] into
    # the test map and core.run wraps the client with a per-run tracer
    # writing <run>/trace.jsonl (see doc/observability.md)
    return t


main_all = standard_test_all(dgraph_test, SUPPORTED_WORKLOADS,
                             name="jepsen-dgraph")


def _dgraph_opts(p):
    p.add_argument("--version", default=DEFAULT_VERSION)


main = cli.single_test_cmd(
    standard_test_fn(dgraph_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS, extra_faults=("move-tablet",),
                    extra=_dgraph_opts),
    name="jepsen-dgraph")


if __name__ == "__main__":
    import sys
    sys.exit(main())
