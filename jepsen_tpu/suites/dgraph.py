"""Dgraph test suite (reference: dgraph/ in jaydenwen123/jepsen — the
largest reference suite: dgraph/src/jepsen/dgraph/{set,bank,delete,
long_fork,upsert,sequential}.clj over a zero+alpha cluster,
dgraph/src/jepsen/dgraph/support.clj for DB automation).

The client rides Dgraph's HTTP API. Set adds are single JSON mutations
with ``commitNow``; register writes and CAS are **upsert blocks** — a
DQL query binding the key's uid/value plus a conditional mutation
(``@if``), executed atomically server-side, the HTTP equivalent of the
reference upsert.clj's transactional upserts. Reads query by indexed
key predicate.

DB automation installs the dgraph binary, runs ``dgraph zero`` on the
first node (``--replicas N`` for one raft group) and ``dgraph alpha``
on every node pointing at it — support.clj's zero/alpha bring-up.
"""
from __future__ import annotations

import logging
import urllib.error

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_json

logger = logging.getLogger("jepsen.dgraph")

DEFAULT_VERSION = "23.1.1"
DIR = "/opt/dgraph"
ZERO_LOG = f"{DIR}/zero.log"
ALPHA_LOG = f"{DIR}/alpha.log"
ZERO_PID = f"{DIR}/zero.pid"
ALPHA_PID = f"{DIR}/alpha.pid"
ALPHA_HTTP_PORT = 8080
ZERO_GRPC_PORT = 5080

SCHEMA = "key: int @index(int) .\nval: int .\nel: int @index(int) .\n"


def binary_url(version: str) -> str:
    return (f"https://github.com/dgraph-io/dgraph/releases/download/"
            f"v{version}/dgraph-linux-amd64.tar.gz")


class DgraphDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing dgraph %s", node, self.version)
        cu.install_archive(binary_url(self.version), DIR)
        nodes = test.get("nodes") or []
        zero_node = nodes[0] if nodes else node
        if node == zero_node:
            cu.start_daemon(
                {"logfile": ZERO_LOG, "pidfile": ZERO_PID, "chdir": DIR},
                f"{DIR}/dgraph", "zero", "--my", f"{node}:{ZERO_GRPC_PORT}",
                "--replicas", str(len(nodes) or 1))
            cu.await_tcp_port(ZERO_GRPC_PORT, host=zero_node)
        self.start(test, node)
        cu.await_tcp_port(ALPHA_HTTP_PORT, host=node)
        if node == zero_node:
            http_json(f"http://{node}:{ALPHA_HTTP_PORT}/alter",
                      raw_body=SCHEMA.encode(), timeout_s=30)

    def teardown(self, test, node):
        self.kill(test, node)
        for d in ("p", "w", "zw"):
            cu.rm_rf(f"{DIR}/{d}")

    def start(self, test, node):
        nodes = test.get("nodes") or []
        zero_node = nodes[0] if nodes else node
        return cu.start_daemon(
            {"logfile": ALPHA_LOG, "pidfile": ALPHA_PID, "chdir": DIR},
            f"{DIR}/dgraph", "alpha", "--my", f"{node}:7080",
            "--zero", f"{zero_node}:{ZERO_GRPC_PORT}",
            "--security", "whitelist=0.0.0.0/0")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/dgraph", ALPHA_PID)
        cu.stop_daemon(f"{DIR}/dgraph", ZERO_PID)
        cu.grepkill("dgraph")

    def pause(self, test, node):
        cu.grepkill("dgraph", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("dgraph", sig="CONT")

    def log_files(self, test, node):
        return [ZERO_LOG, ALPHA_LOG]


class DgraphClient(Client):
    """Register/set ops via HTTP upsert blocks and DQL queries."""

    def __init__(self, timeout_s: float = 10.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return DgraphClient(self.timeout_s, node)

    def _mutate(self, body: dict):
        doc = http_json(
            f"http://{self.node}:{ALPHA_HTTP_PORT}/mutate?commitNow=true",
            body, timeout_s=self.timeout_s)
        errs = doc.get("errors")
        if errs:
            raise DgraphError(str(errs))
        return doc

    def _query(self, q: str):
        doc = http_json(f"http://{self.node}:{ALPHA_HTTP_PORT}/query",
                        raw_body=q.encode(),
                        headers={"Content-Type": "application/dql"},
                        timeout_s=self.timeout_s)
        errs = doc.get("errors")
        if errs:
            raise DgraphError(str(errs))
        return doc.get("data") or {}

    def _read_register(self, k):
        data = self._query(
            "{ q(func: eq(key, %d)) { val } }" % k)
        rows = data.get("q") or []
        return rows[0].get("val") if rows else None

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "add":
                self._mutate({"set": [{"el": v}]})
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                data = self._query("{ q(func: has(el)) { el } }")
                elems = sorted(row["el"] for row in (data.get("q") or []))
                return {**op, "type": "ok", "value": elems}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self._read_register(k)]}
            if f == "write":
                k, val = v
                # upsert: bind the key's uid, write through it (or create)
                self._mutate({
                    "query": "{ q(func: eq(key, %d)) { u as uid } }" % k,
                    "set": [{"uid": "uid(u)", "key": k, "val": val}]})
                return {**op, "type": "ok"}
            if f == "cas":
                # a real dgraph txn: snapshot read at start_ts, write, then
                # commit with conflict keys — aborts on concurrent writers
                # (the reference client's txn shape, upsert.clj pattern)
                k, (old, new) = v
                q = http_json(
                    f"http://{self.node}:{ALPHA_HTTP_PORT}/query",
                    raw_body=(b"{ q(func: eq(key, %d)) { uid val } }"
                              % k),
                    headers={"Content-Type": "application/dql"},
                    timeout_s=self.timeout_s)
                rows = (q.get("data") or {}).get("q") or []
                start_ts = (q.get("extensions") or {}).get(
                    "txn", {}).get("start_ts")
                if not rows or rows[0].get("val") != old or not start_ts:
                    return {**op, "type": "fail"}
                mut = http_json(
                    f"http://{self.node}:{ALPHA_HTTP_PORT}/mutate"
                    f"?startTs={start_ts}",
                    {"set": [{"uid": rows[0]["uid"], "val": new}]},
                    timeout_s=self.timeout_s)
                if mut.get("errors"):
                    return {**op, "type": "fail",
                            "error": ["txn", str(mut["errors"])]}
                txn = (mut.get("extensions") or {}).get("txn", {})
                try:
                    commit = http_json(
                        f"http://{self.node}:{ALPHA_HTTP_PORT}/commit"
                        f"?startTs={start_ts}",
                        {"keys": txn.get("keys") or [],
                         "preds": txn.get("preds") or []},
                        timeout_s=self.timeout_s)
                except urllib.error.HTTPError as e:
                    if e.code == 409:  # aborted: lost the conflict race
                        return {**op, "type": "fail"}
                    raise
                if commit.get("errors"):
                    return {**op, "type": "fail",
                            "error": ["txn", str(commit["errors"])]}
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except DgraphError as e:
            # txn conflicts abort server-side: definite failure
            if "conflict" in str(e).lower() or "aborted" in str(e).lower():
                return {**op, "type": "fail", "error": ["txn", str(e)]}
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["dgraph", str(e)]}
        except urllib.error.HTTPError as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


class DgraphError(Exception):
    pass


SUPPORTED_WORKLOADS = ("set", "register")


def dgraph_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="dgraph", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": DgraphDB(o.get("version", DEFAULT_VERSION)),
            "client": DgraphClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(dgraph_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-dgraph")


if __name__ == "__main__":
    import sys
    sys.exit(main())
