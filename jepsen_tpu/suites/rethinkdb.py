"""RethinkDB test suite (reference: rethinkdb/src/jepsen/rethinkdb.clj
+ rethinkdb/document_cas.clj — a document store whose per-document
atomic update enables a linearizable CAS register, tested across
write-ack/read-mode combinations).

The client rides the bundled ReQL wire driver (``_reql.py``). Register
ops follow document_cas.clj:71-105: read is ``get(k)["val"].default
(nil)`` at the configured read mode ("majority" for linearizable
reads); write is an insert with ``conflict: update``; CAS runs the
atomic update lambda ``branch(eq(row["val"], old), {"val": new},
error("abort"))`` and succeeds iff exactly one row reports
``replaced`` with zero errors.

DB automation per rethinkdb.clj:52-95: apt repo install, a config file
with ``join=`` lines for every peer, service start.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites import _reql as r
from jepsen_tpu.suites._reql import ReqlConnection, ReqlError

logger = logging.getLogger("jepsen.rethinkdb")

DRIVER_PORT = 28015
CLUSTER_PORT = 29015
DB_NAME = "jepsen"
TABLE = "cas"
CAS_ABORT_SENTINEL = "jepsen-cas-precondition-abort"
CONF = "/etc/rethinkdb/instances.d/jepsen.conf"
LOG_FILE = "/var/log/rethinkdb"


def config(test: dict, node: str) -> str:
    """Config with join= lines for every peer (rethinkdb.clj:67-87)."""
    lines = ["bind=all",
             f"server-name={node}",
             f"directory=/var/lib/rethinkdb/jepsen"]
    lines += [f"join={n}:{CLUSTER_PORT}" for n in (test.get("nodes") or [])
              if n != node]
    return "\n".join(lines) + "\n"


class RethinkDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Apt install + join-configured service (rethinkdb.clj:52-95)."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing rethinkdb", node)
        os_setup.install(["rethinkdb"])
        cu.mkdir("/etc/rethinkdb/instances.d")
        cu.write_file(config(test, node), CONF)
        control.exec_("service", "rethinkdb", "restart")
        cu.await_tcp_port(DRIVER_PORT, host=node, timeout_s=300.0)
        core.synchronize(test, timeout_s=600.0)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf("/var/lib/rethinkdb/jepsen")

    def start(self, test, node):
        control.exec_("service", "rethinkdb", "start")

    def kill(self, test, node):
        control.exec_(control.lit(
            "service rethinkdb stop >/dev/null 2>&1 || true"))
        cu.grepkill("rethinkdb")

    def pause(self, test, node):
        cu.grepkill("rethinkdb", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("rethinkdb", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class RethinkDBClient(Client):
    """Document-CAS register client (document_cas.clj:40-105)."""

    def __init__(self, write_acks: str = "majority",
                 read_mode: str = "majority", timeout_s: float = 10.0,
                 node: str | None = None):
        self.write_acks = write_acks
        self.read_mode = read_mode
        self.timeout_s = timeout_s
        self.node = node
        self.conn: ReqlConnection | None = None

    def open(self, test, node):
        c = RethinkDBClient(self.write_acks, self.read_mode,
                            self.timeout_s, node)
        c.conn = ReqlConnection(node, DRIVER_PORT, timeout_s=self.timeout_s)
        return c

    def setup(self, test):
        try:
            self.conn.run(r.db_create(DB_NAME))
        except ReqlError:
            pass  # already exists
        try:
            self.conn.run(r.table_create(
                r.db(DB_NAME), TABLE,
                replicas=len(test.get("nodes") or []) or None))
        except ReqlError:
            pass
        # table-level write acks (document_cas.clj set-write-acks!)
        try:
            self.conn.run([r.UPDATE, [
                [r.TABLE, [[r.DB, ["rethinkdb"]], "table_config"]],
                {"write_acks": self.write_acks}]])
        except ReqlError:
            pass

    def _row(self, k):
        return r.get(r.table(r.db(DB_NAME), TABLE,
                             read_mode=self.read_mode), int(k))

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "read":
                k, _ = v
                out = self.conn.run(
                    r.default(r.get_field(self._row(k), "val"), None))
                return {**op, "type": "ok",
                        "value": [k, int(out) if out is not None else None]}
            if f == "write":
                k, val = v
                self.conn.run(r.insert(
                    r.table(r.db(DB_NAME), TABLE),
                    {"id": int(k), "val": int(val)}, conflict="update"))
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                res = self.conn.run(r.update(
                    self._row(k),
                    r.func(r.branch(
                        r.eq(r.get_field(r.var(1), "val"), int(old)),
                        {"val": int(new)},
                        r.error(CAS_ABORT_SENTINEL)))))
                ok = (isinstance(res, dict) and res.get("errors") == 0
                      and res.get("replaced") == 1)
                return {**op, "type": "ok" if ok else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except ReqlError as e:
            # reads are safe to fail; the CAS lambda's own unique abort
            # sentinel is a definite precondition miss; any other runtime
            # error on a write/cas (e.g. "lost contact with primary
            # replica") is indeterminate (document_cas.clj with-errors
            # #{:read}) — a generic substring match would misclassify
            # server messages that merely mention "abort"
            if f == "read" or any(CAS_ABORT_SENTINEL in str(m)
                                  for m in (e.messages or [])):
                return {**op, "type": "fail", "error": ["reql", str(e)]}
            return {**op, "type": "info", "error": ["reql", str(e)]}
        except (TimeoutError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


SUPPORTED_WORKLOADS = ("register",)


def rethinkdb_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="rethinkdb",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": RethinkDB(),
            "client": RethinkDBClient(o.get("write_acks", "majority"),
                                      o.get("read_mode", "majority")),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(rethinkdb_test, extra_keys=("write_acks", "read_mode")),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: (
                        p.add_argument("--write-acks", dest="write_acks",
                                       default="majority",
                                       choices=["single", "majority"]),
                        p.add_argument("--read-mode", dest="read_mode",
                                       default="majority",
                                       choices=["single", "majority",
                                                "outdated"]))),
    name="jepsen-rethinkdb")


if __name__ == "__main__":
    import sys
    sys.exit(main())
