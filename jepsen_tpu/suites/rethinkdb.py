"""RethinkDB test suite (reference: rethinkdb/src/jepsen/rethinkdb.clj
+ rethinkdb/document_cas.clj — a document store whose per-document
atomic update enables a linearizable CAS register, tested across
write-ack/read-mode combinations).

The client rides the bundled ReQL wire driver (``_reql.py``). Register
ops follow document_cas.clj:71-105: read is ``get(k)["val"].default
(nil)`` at the configured read mode ("majority" for linearizable
reads); write is an insert with ``conflict: update``; CAS runs the
atomic update lambda ``branch(eq(row["val"], old), {"val": new},
error("abort"))`` and succeeds iff exactly one row reports
``replaced`` with zero errors.

DB automation per rethinkdb.clj:52-95: apt repo install, a config file
with ``join=`` and per-node ``server-tag=`` lines, service start.

Beyond the register: ``set`` (doc-per-element) and ``counter`` (atomic
in-document add) workloads, and ``--fault reconfigure`` — the random
replica/primary topology churn nemesis (rethinkdb.clj:180-232) over
the RECONFIGURE admin term.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu import nemesis as nemesis_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_all, standard_test_fn)
from jepsen_tpu.suites import _reql as r
from jepsen_tpu.suites._reql import ReqlConnection, ReqlError

logger = logging.getLogger("jepsen.rethinkdb")

DRIVER_PORT = 28015
CLUSTER_PORT = 29015
DB_NAME = "jepsen"
TABLE = "cas"
SET_TABLE = "elements"
COUNTER_TABLE = "counter"


def active_table(test: dict) -> str:
    """The table the running workload lives in (reconfigure targets it
    too); routed by test-map markers, matching how invoke routes ops."""
    if test.get("counter"):
        return COUNTER_TABLE
    if test.get("rethinkdb-set"):
        return SET_TABLE
    return TABLE
CAS_ABORT_SENTINEL = "jepsen-cas-precondition-abort"
CONF = "/etc/rethinkdb/instances.d/jepsen.conf"
LOG_FILE = "/var/log/rethinkdb"


def config(test: dict, node: str) -> str:
    """Config with join= lines for every peer (rethinkdb.clj:67-87)."""
    lines = ["bind=all",
             f"server-name={node}",
             # per-node server tags are what reconfigure! targets
             # replicas by (rethinkdb.clj:86,184-188)
             f"server-tag={node}",
             f"directory=/var/lib/rethinkdb/jepsen"]
    lines += [f"join={n}:{CLUSTER_PORT}" for n in (test.get("nodes") or [])
              if n != node]
    return "\n".join(lines) + "\n"


class RethinkDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Apt install + join-configured service (rethinkdb.clj:52-95)."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing rethinkdb", node)
        os_setup.install(["rethinkdb"])
        cu.mkdir("/etc/rethinkdb/instances.d")
        cu.write_file(config(test, node), CONF)
        control.exec_("service", "rethinkdb", "restart")
        cu.await_tcp_port(DRIVER_PORT, host=node, timeout_s=300.0)
        core.synchronize(test, timeout_s=600.0)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf("/var/lib/rethinkdb/jepsen")

    def start(self, test, node):
        control.exec_("service", "rethinkdb", "start")

    def kill(self, test, node):
        control.exec_(control.lit(
            "service rethinkdb stop >/dev/null 2>&1 || true"))
        cu.grepkill("rethinkdb")

    def pause(self, test, node):
        cu.grepkill("rethinkdb", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("rethinkdb", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class RethinkDBClient(Client):
    """Document-CAS register client (document_cas.clj:40-105)."""

    def __init__(self, write_acks: str = "majority",
                 read_mode: str = "majority", timeout_s: float = 10.0,
                 node: str | None = None):
        self.write_acks = write_acks
        self.read_mode = read_mode
        self.timeout_s = timeout_s
        self.node = node
        self.conn: ReqlConnection | None = None

    def open(self, test, node):
        c = RethinkDBClient(self.write_acks, self.read_mode,
                            self.timeout_s, node)
        c.conn = ReqlConnection(node, DRIVER_PORT, timeout_s=self.timeout_s)
        return c

    def setup(self, test):
        try:
            self.conn.run(r.db_create(DB_NAME))
        except ReqlError:
            pass  # already exists
        try:
            self.conn.run(r.table_create(
                r.db(DB_NAME), active_table(test),
                replicas=len(test.get("nodes") or []) or None))
        except ReqlError:
            pass
        if test.get("counter"):
            try:  # single counter row, starts at 0
                self.conn.run(r.insert(
                    r.table(r.db(DB_NAME), active_table(test)),
                    {"id": 0, "val": 0}, conflict="error"))
            except ReqlError:
                pass
        # table-level write acks (document_cas.clj set-write-acks!)
        try:
            self.conn.run([r.UPDATE, [
                [r.TABLE, [[r.DB, ["rethinkdb"]], "table_config"]],
                {"write_acks": self.write_acks}]])
        except ReqlError:
            pass

    def _row(self, k):
        return r.get(r.table(r.db(DB_NAME), TABLE,
                             read_mode=self.read_mode), int(k))

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if test.get("counter") and f == "add":
                # atomic in-document add (the per-document atomicity the
                # register CAS also rides); a skipped update (missing
                # counter row) must NOT ack, or the checker's
                # acknowledged-sum bound convicts a healthy run
                res = self.conn.run(r.update(
                    r.get(r.table(r.db(DB_NAME), COUNTER_TABLE), 0),
                    r.func({"val": r.add(r.get_field(r.var(1), "val"),
                                         int(v))})))
                applied = (isinstance(res, dict) and res.get("errors") == 0
                           and res.get("replaced") == 1)
                if applied:
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": ["not-applied", res]}
            if test.get("counter") and f == "read" and v is None:
                out = self.conn.run(r.default(r.get_field(
                    r.get(r.table(r.db(DB_NAME), COUNTER_TABLE,
                                  read_mode=self.read_mode), 0),
                    "val"), 0))
                return {**op, "type": "ok", "value": int(out or 0)}
            if f == "add":
                # set adds: one doc per element, id = the element
                self.conn.run(r.insert(
                    r.table(r.db(DB_NAME), SET_TABLE), {"id": int(v)},
                    conflict="update"))
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                out = self.conn.run(r.coerce_to(
                    r.map_(r.table(r.db(DB_NAME), SET_TABLE,
                                   read_mode=self.read_mode),
                           r.func(r.get_field(r.var(1), "id"))),
                    "array"))
                return {**op, "type": "ok",
                        "value": sorted(int(x) for x in out or [])}
            if f == "read":
                k, _ = v
                out = self.conn.run(
                    r.default(r.get_field(self._row(k), "val"), None))
                return {**op, "type": "ok",
                        "value": [k, int(out) if out is not None else None]}
            if f == "write":
                k, val = v
                self.conn.run(r.insert(
                    r.table(r.db(DB_NAME), TABLE),
                    {"id": int(k), "val": int(val)}, conflict="update"))
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                res = self.conn.run(r.update(
                    self._row(k),
                    r.func(r.branch(
                        r.eq(r.get_field(r.var(1), "val"), int(old)),
                        {"val": int(new)},
                        r.error(CAS_ABORT_SENTINEL)))))
                ok = (isinstance(res, dict) and res.get("errors") == 0
                      and res.get("replaced") == 1)
                return {**op, "type": "ok" if ok else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except ReqlError as e:
            # reads are safe to fail; the CAS lambda's own unique abort
            # sentinel is a definite precondition miss; any other runtime
            # error on a write/cas (e.g. "lost contact with primary
            # replica") is indeterminate (document_cas.clj with-errors
            # #{:read}) — a generic substring match would misclassify
            # server messages that merely mention "abort"
            if f == "read" or any(CAS_ABORT_SENTINEL in str(m)
                                  for m in (e.messages or [])):
                return {**op, "type": "fail", "error": ["reql", str(e)]}
            return {**op, "type": "info", "error": ["reql", str(e)]}
        except (TimeoutError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


# ---------------------------------------------------------------------------
# Reconfigure nemesis (rethinkdb.clj:180-232): randomly re-replicate and
# re-primary the workload's table through the admin reconfigure term
# ---------------------------------------------------------------------------

class ReconfigureNemesis(nemesis_mod.Nemesis):
    """Each op picks a random nonempty replica set and primary (by
    server tag = node name) and reconfigures the active table to it;
    tag-not-found / servers-unreachable errors retry up to 10 times
    (rethinkdb.clj:195-232)."""

    RETRYABLE = ("Could not find any servers with server tag",
                 "currently unreachable")

    def __init__(self, rng=None, timeout_s: float = 5.0):
        import random as _random
        self.rng = rng or _random.Random()
        self.timeout_s = timeout_s

    def fs(self):
        return {"reconfigure"}

    def _reconfigure_once(self, test):
        nodes = list(test.get("nodes") or [])
        size = self.rng.randint(1, len(nodes))
        replicas = self.rng.sample(nodes, size)
        primary = self.rng.choice(replicas)
        conn = self._connect(primary)
        try:
            res = conn.run(r.reconfigure(
                r.table(r.db(DB_NAME), active_table(test)),
                {n: 1 for n in replicas}, primary))
            if not (isinstance(res, dict) and res.get("reconfigured") == 1):
                # surfaces through invoke's ReqlError handling as a
                # non-retryable ["error", ...] value (an assert would
                # escape it — and vanish under -O)
                raise ReqlError(0, [f"unexpected reconfigure result: {res}"])
            return {"replicas": replicas, "primary": primary}
        finally:
            conn.close()

    def _connect(self, primary):
        return ReqlConnection(primary, DRIVER_PORT, timeout_s=self.timeout_s)

    def invoke(self, test, op):
        last = None
        for _ in range(10):
            try:
                return {**op, "type": "info",
                        "value": self._reconfigure_once(test)}
            except ReqlError as e:
                last = e
                if not any(pat in str(e) for pat in self.RETRYABLE):
                    break
            except (TimeoutError, ConnectionError, OSError) as e:
                return {**op, "type": "info", "value": "timeout",
                        "error": ["net", str(e)]}
        return {**op, "type": "info", "value": ["error", str(last)]}


def reconfigure_package(opts: dict) -> dict:
    """--fault reconfigure: periodic topology churn on the active
    table."""
    from jepsen_tpu import generator as gen
    interval = opts.get("interval", 10.0)
    return {
        "nemesis": ReconfigureNemesis(),
        "generator": gen.stagger(interval, gen.repeat(
            {"type": "info", "f": "reconfigure", "value": None})),
        "final_generator": None,
        "perf": {"name": "reconfigure", "fs": {"reconfigure"},
                 "start": set(), "stop": set()},
    }


SUPPORTED_WORKLOADS = ("register", "set", "counter")


def _set_workload(base: dict) -> dict:
    """The shared set kit plus the table-routing marker."""
    from jepsen_tpu.workloads import set_workload
    return {**set_workload.workload(base,
                                    accelerator=base["accelerator"]),
            "rethinkdb-set": True}


def rethinkdb_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="rethinkdb",
        supported_workloads=SUPPORTED_WORKLOADS,
        extra_workloads={"set": _set_workload},
        fault_packages={"reconfigure": reconfigure_package},
        make_real=lambda o: {
            "db": RethinkDB(),
            "client": RethinkDBClient(o.get("write_acks", "majority"),
                                      o.get("read_mode", "majority")),
            "os": Debian()})


main_all = standard_test_all(rethinkdb_test, SUPPORTED_WORKLOADS,
                             name="jepsen-rethinkdb")

main = cli.single_test_cmd(
    standard_test_fn(rethinkdb_test, extra_keys=("write_acks", "read_mode")),
    standard_opt_fn(SUPPORTED_WORKLOADS, extra_faults=("reconfigure",),
                    extra=lambda p: (
                        p.add_argument("--write-acks", dest="write_acks",
                                       default="majority",
                                       choices=["single", "majority"]),
                        p.add_argument("--read-mode", dest="read_mode",
                                       default="majority",
                                       choices=["single", "majority",
                                                "outdated"]))),
    name="jepsen-rethinkdb")


if __name__ == "__main__":
    import sys
    sys.exit(main())
