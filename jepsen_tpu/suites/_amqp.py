"""Minimal AMQP 0-9-1 wire client for the rabbitmq suite (reference:
rabbitmq/src/jepsen/rabbitmq.clj rides the langohr JVM driver; this
module is the from-scratch equivalent, like ``_mysql.py`` /
``_postgres.py`` / ``_resp.py`` for their families).

Implements exactly the subset the queue workload needs: connection
negotiation (Start/Tune/Open with PLAIN auth), channel open, publisher
confirms (``confirm.select`` + waiting for ``basic.ack``), durable
``queue.declare``, ``basic.publish`` with persistent delivery-mode,
``basic.get`` + client ``basic.ack``, and ``queue.purge``. Heartbeats
are negotiated off. Server-initiated ``channel.close`` /
``connection.close`` raise :class:`AmqpError` after the protocol-
mandated close-ok handshake.
"""
from __future__ import annotations

import socket
import struct

PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"
FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8
FRAME_END = 0xCE

# (class, method) ids used below
CONN_START = (10, 10)
CONN_START_OK = (10, 11)
CONN_TUNE = (10, 30)
CONN_TUNE_OK = (10, 31)
CONN_OPEN = (10, 40)
CONN_OPEN_OK = (10, 41)
CONN_CLOSE = (10, 50)
CONN_CLOSE_OK = (10, 51)
CHAN_OPEN = (20, 10)
CHAN_OPEN_OK = (20, 11)
CHAN_CLOSE = (20, 40)
CHAN_CLOSE_OK = (20, 41)
QUEUE_DECLARE = (50, 10)
QUEUE_DECLARE_OK = (50, 11)
QUEUE_PURGE = (50, 30)
QUEUE_PURGE_OK = (50, 31)
BASIC_PUBLISH = (60, 40)
BASIC_RETURN = (60, 50)
BASIC_GET = (60, 70)
BASIC_GET_OK = (60, 71)
BASIC_GET_EMPTY = (60, 72)
BASIC_ACK = (60, 80)
BASIC_REJECT = (60, 90)
BASIC_NACK = (60, 120)
CONFIRM_SELECT = (85, 10)
CONFIRM_SELECT_OK = (85, 11)


class AmqpError(Exception):
    """A server channel/connection close: ``.code`` and ``.text``."""

    def __init__(self, code: int, text: str):
        super().__init__(f"{code} {text}")
        self.code = code
        self.text = text


def shortstr(s: str) -> bytes:
    data = s.encode()
    return struct.pack(">B", len(data)) + data


def longstr(data: bytes) -> bytes:
    return struct.pack(">I", len(data)) + data


def parse_shortstr(buf: bytes, pos: int) -> tuple[str, int]:
    n = buf[pos]
    return buf[pos + 1:pos + 1 + n].decode(), pos + 1 + n


class AmqpConnection:
    """One connection + one channel (channel 1), the shape every op in
    the rabbitmq suite uses (rabbitmq.clj's with-ch per invoke)."""

    def __init__(self, host: str, port: int = 5672, user: str = "guest",
                 password: str = "guest", vhost: str = "/",
                 timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self._publish_seq = 0  # confirm-mode sequence number
        try:
            self._handshake(user, password, vhost)
            self._open_channel()
        except BaseException:
            self.sock.close()
            raise

    # -- framing ----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        from jepsen_tpu.suites._wire import recv_exact
        return recv_exact(self.sock, n)

    def _read_frame(self) -> tuple[int, int, bytes]:
        ftype, channel, size = struct.unpack(">BHI", self._recv_exact(7))
        payload = self._recv_exact(size)
        end = self._recv_exact(1)
        if end[0] != FRAME_END:
            raise ConnectionError(f"bad frame end {end!r}")
        return ftype, channel, payload

    def _send_frame(self, ftype: int, channel: int, payload: bytes) -> None:
        self.sock.sendall(struct.pack(">BHI", ftype, channel, len(payload))
                          + payload + bytes([FRAME_END]))

    def _send_method(self, channel: int, cm: tuple[int, int],
                     args: bytes = b"") -> None:
        self._send_frame(FRAME_METHOD, channel,
                         struct.pack(">HH", *cm) + args)

    def _read_method(self) -> tuple[tuple[int, int], bytes, int]:
        """Next method frame (skipping heartbeats); raises on close."""
        while True:
            ftype, channel, payload = self._read_frame()
            if ftype == FRAME_HEARTBEAT:
                continue
            if ftype != FRAME_METHOD:
                raise ConnectionError(f"unexpected frame type {ftype}")
            cm = struct.unpack(">HH", payload[:4])
            args = payload[4:]
            if cm == CHAN_CLOSE:
                code = struct.unpack(">H", args[:2])[0]
                text, _ = parse_shortstr(args, 2)
                self._send_method(channel, CHAN_CLOSE_OK)
                raise AmqpError(code, text)
            if cm == CONN_CLOSE:
                code = struct.unpack(">H", args[:2])[0]
                text, _ = parse_shortstr(args, 2)
                self._send_method(0, CONN_CLOSE_OK)
                raise AmqpError(code, text)
            return cm, args, channel

    def _expect(self, cm: tuple[int, int]) -> bytes:
        got, args, _channel = self._read_method()
        if got != cm:
            raise ConnectionError(f"expected {cm}, got {got}")
        return args

    # -- connection negotiation ------------------------------------------

    def _handshake(self, user: str, password: str, vhost: str) -> None:
        self.sock.sendall(PROTOCOL_HEADER)
        self._expect(CONN_START)
        plain = b"\x00" + user.encode() + b"\x00" + password.encode()
        self._send_method(0, CONN_START_OK,
                          longstr(b"")              # client-properties {}
                          + shortstr("PLAIN")
                          + longstr(plain)
                          + shortstr("en_US"))
        args = self._expect(CONN_TUNE)
        channel_max, frame_max, _hb = struct.unpack(">HIH", args[:8])
        # echo the server's limits; heartbeat 0 = disabled
        self._send_method(0, CONN_TUNE_OK,
                          struct.pack(">HIH", channel_max, frame_max, 0))
        self._send_method(0, CONN_OPEN,
                          shortstr(vhost) + shortstr("") + b"\x00")
        self._expect(CONN_OPEN_OK)

    def _open_channel(self) -> None:
        self._send_method(1, CHAN_OPEN, shortstr(""))
        self._expect(CHAN_OPEN_OK)

    # -- queue ops --------------------------------------------------------

    def confirm_select(self) -> None:
        """Publisher-confirm mode (rabbitmq.clj lco/select)."""
        self._send_method(1, CONFIRM_SELECT, b"\x00")  # nowait=false
        self._expect(CONFIRM_SELECT_OK)

    def queue_declare(self, queue: str, durable: bool = True) -> None:
        bits = 0x02 if durable else 0x00  # passive|durable|excl|auto-del|nowait
        self._send_method(1, QUEUE_DECLARE,
                          struct.pack(">H", 0) + shortstr(queue)
                          + bytes([bits]) + longstr(b""))
        self._expect(QUEUE_DECLARE_OK)

    def queue_purge(self, queue: str) -> int:
        self._send_method(1, QUEUE_PURGE,
                          struct.pack(">H", 0) + shortstr(queue) + b"\x00")
        args = self._expect(QUEUE_PURGE_OK)
        return struct.unpack(">I", args[:4])[0]

    def publish(self, queue: str, body: bytes, mandatory: bool = True,
                persistent: bool = True) -> bool:
        """basic.publish to the default exchange + wait for the broker's
        confirm (rabbitmq.clj:155-165). Returns True on basic.ack, False
        on basic.nack or a mandatory-unroutable basic.return."""
        self._publish_seq += 1
        bits = 0x01 if mandatory else 0x00
        self._send_method(1, BASIC_PUBLISH,
                          struct.pack(">H", 0) + shortstr("")
                          + shortstr(queue) + bytes([bits]))
        # content header: class, weight, body size, flags, delivery-mode
        flags = 0x1000 if persistent else 0  # delivery-mode property bit
        header = struct.pack(">HHQH", 60, 0, len(body), flags)
        if persistent:
            header += bytes([2])
        self._send_frame(FRAME_HEADER, 1, header)
        self._send_frame(FRAME_BODY, 1, body)
        returned = False
        while True:
            cm, args, _ = self._read_method()
            if cm == BASIC_RETURN:
                # unroutable; a content header follows, then as many
                # body frames as its body-size requires (possibly none)
                ftype, _, hdr = self._read_frame()
                if ftype != FRAME_HEADER:
                    raise ConnectionError("expected returned-msg header")
                body_size = struct.unpack(">Q", hdr[4:12])[0]
                got = 0
                while got < body_size:
                    ftype, _, chunk = self._read_frame()
                    if ftype != FRAME_BODY:
                        raise ConnectionError("expected returned-msg body")
                    got += len(chunk)
                returned = True
                continue
            if cm == BASIC_ACK:
                return not returned
            if cm == BASIC_NACK:
                return False
            raise ConnectionError(f"unexpected method {cm} awaiting confirm")

    def get(self, queue: str, no_ack: bool = False):
        """basic.get; returns (delivery_tag, body) or None when empty."""
        self._send_method(1, BASIC_GET,
                          struct.pack(">H", 0) + shortstr(queue)
                          + (b"\x01" if no_ack else b"\x00"))
        cm, args, _ = self._read_method()
        if cm == BASIC_GET_EMPTY:
            return None
        if cm != BASIC_GET_OK:
            raise ConnectionError(f"expected get-ok, got {cm}")
        delivery_tag = struct.unpack(">Q", args[:8])[0]
        ftype, _, payload = self._read_frame()
        if ftype != FRAME_HEADER:
            raise ConnectionError("expected content header")
        body_size = struct.unpack(">Q", payload[4:12])[0]
        body = b""
        while len(body) < body_size:
            ftype, _, chunk = self._read_frame()
            if ftype != FRAME_BODY:
                raise ConnectionError("expected content body")
            body += chunk
        return delivery_tag, body

    def ack(self, delivery_tag: int) -> None:
        self._send_method(1, BASIC_ACK,
                          struct.pack(">Q", delivery_tag) + b"\x00")

    def reject(self, delivery_tag: int, requeue: bool = True) -> None:
        """basic.reject — with requeue, returns an unacked message to
        the queue (the semaphore workload's release)."""
        self._send_method(1, BASIC_REJECT,
                          struct.pack(">Q", delivery_tag)
                          + (b"\x01" if requeue else b"\x00"))

    def close(self) -> None:
        from jepsen_tpu.suites._wire import close_quietly
        close_quietly(self.sock)
