"""From-scratch CQL native-protocol client for the YCQL suite family
(reference: yugabyte/src/yugabyte/ycql/client.clj and the per-workload
clients under ycql/ — they ride the cassaforte JVM driver; this is the
same capability over a stdlib socket speaking protocol v4).

Surface kept to what the YCQL workloads need:

* STARTUP/READY handshake (plus PLAIN SASL when the server demands
  AUTHENTICATE)
* QUERY with QUORUM consistency; RESULT parsing for Void, Rows (typed
  decode of int/bigint/counter/varchar/ascii/boolean/double, uuid as a
  hex string), Set_keyspace and Schema_change
* ERROR frames surfaced as :class:`CqlError` with the server's code —
  the YCQL error discipline mirrors the SQL family's: definite
  application failures (LWT not applied, invalid query) fail ops;
  network errors are indeterminate for writes

YCQL transactions span a single statement string
(``BEGIN TRANSACTION ... END TRANSACTION;`` — the reference builds the
same strings, ycql/bank.clj:51-60, ycql/multi_key_acid.clj:49-60),
so the client needs no prepared-statement or batch machinery.
"""
from __future__ import annotations

import socket
import struct

from jepsen_tpu.suites._wire import close_quietly, recv_exact

# protocol v4 opcodes
OP_ERROR = 0x00
OP_STARTUP = 0x01
OP_READY = 0x02
OP_AUTHENTICATE = 0x03
OP_QUERY = 0x07
OP_RESULT = 0x08
OP_AUTH_RESPONSE = 0x0F
OP_AUTH_SUCCESS = 0x10

CONSISTENCY_QUORUM = 0x0004

RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002
RESULT_SET_KEYSPACE = 0x0003
RESULT_SCHEMA_CHANGE = 0x0005

# type option ids (v4 §6)
T_BIGINT = 0x0002
T_BOOLEAN = 0x0004
T_COUNTER = 0x0005
T_DOUBLE = 0x0007
T_FLOAT = 0x0008
T_INT = 0x0009
T_TIMESTAMP = 0x000B
T_VARCHAR = 0x000D
T_ASCII = 0x0001
T_UUID = 0x000C
T_TIMEUUID = 0x000F
T_SMALLINT = 0x0013
T_TINYINT = 0x0014

# response frame flags (v4 §2.2)
FLAG_COMPRESSED = 0x01
FLAG_TRACING = 0x02
FLAG_CUSTOM_PAYLOAD = 0x04
FLAG_WARNING = 0x08


class CqlError(Exception):
    """Server ERROR frame: ``code`` is the CQL error code int."""

    def __init__(self, code: int, message: str):
        super().__init__(f"[{code:#06x}] {message}")
        self.code = code
        self.message = message


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!I", len(b)) + b


def _string_map(m: dict) -> bytes:
    out = struct.pack("!H", len(m))
    for k, v in m.items():
        out += _string(k) + _string(v)
    return out


def _decode_value(type_id: int, raw: bytes):
    if raw is None:
        return None
    if type_id in (T_INT,):
        return struct.unpack("!i", raw)[0]
    if type_id in (T_BIGINT, T_COUNTER, T_TIMESTAMP):
        return struct.unpack("!q", raw)[0]
    if type_id == T_SMALLINT:
        return struct.unpack("!h", raw)[0]
    if type_id == T_TINYINT:
        return struct.unpack("!b", raw)[0]
    if type_id == T_BOOLEAN:
        return raw != b"\x00"
    if type_id == T_DOUBLE:
        return struct.unpack("!d", raw)[0]
    if type_id == T_FLOAT:
        return struct.unpack("!f", raw)[0]
    if type_id in (T_VARCHAR, T_ASCII):
        return raw.decode()
    if type_id in (T_UUID, T_TIMEUUID):
        return raw.hex()
    return raw  # unknown types surface as bytes


class CQLConnection:
    """One authenticated CQL connection; ``query`` returns a list of
    row dicts (column name → decoded value), or [] for non-Rows."""

    def __init__(self, host: str, port: int = 9042, user: str = "",
                 password: str = "", timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        self._stream = 0
        try:
            self._startup(user, password)
        except BaseException:
            close_quietly(self.sock)
            raise

    # -- framing ----------------------------------------------------------

    def _send_frame(self, opcode: int, body: bytes) -> None:
        header = struct.pack("!BBhBI", 0x04, 0x00, self._stream, opcode,
                             len(body))
        self.sock.sendall(header + body)

    def _read_frame(self) -> tuple[int, bytes]:
        header = recv_exact(self.sock, 9)
        _ver, flags, _stream, opcode, length = struct.unpack("!BBhBI",
                                                             header)
        body = recv_exact(self.sock, length) if length else b""
        if flags & FLAG_COMPRESSED:
            # never negotiated in STARTUP; a server that compresses
            # anyway has desynced the connection
            raise CqlError(0x000A, "unexpected compressed frame")
        if flags & FLAG_TRACING:
            body = body[16:]  # tracing session uuid
        if flags & FLAG_WARNING:
            # [string list] of warnings prefixes the body (v4 §2.2)
            n = struct.unpack("!H", body[:2])[0]
            off = 2
            for _ in range(n):
                slen = struct.unpack("!H", body[off:off + 2])[0]
                off += 2 + slen
            body = body[off:]
        if flags & FLAG_CUSTOM_PAYLOAD:
            # [bytes map] prefixes the body
            n = struct.unpack("!H", body[:2])[0]
            off = 2
            for _ in range(n):
                klen = struct.unpack("!H", body[off:off + 2])[0]
                off += 2 + klen
                vlen = struct.unpack("!i", body[off:off + 4])[0]
                off += 4 + max(vlen, 0)
            body = body[off:]
        if opcode == OP_ERROR:
            code = struct.unpack("!I", body[:4])[0]
            mlen = struct.unpack("!H", body[4:6])[0]
            raise CqlError(code, body[6:6 + mlen].decode())
        return opcode, body

    # -- handshake --------------------------------------------------------

    def _startup(self, user: str, password: str) -> None:
        self._send_frame(OP_STARTUP, _string_map({"CQL_VERSION": "3.0.0"}))
        opcode, body = self._read_frame()
        if opcode == OP_AUTHENTICATE:
            # PLAIN SASL: \0user\0password (the only scheme yugabyte's
            # password authenticator speaks)
            token = b"\x00" + user.encode() + b"\x00" + password.encode()
            self._send_frame(OP_AUTH_RESPONSE,
                             struct.pack("!I", len(token)) + token)
            opcode, body = self._read_frame()
            if opcode != OP_AUTH_SUCCESS:
                raise CqlError(0x0100, f"auth failed (opcode {opcode})")
        elif opcode != OP_READY:
            raise CqlError(0x000A, f"unexpected startup opcode {opcode}")

    # -- queries ----------------------------------------------------------

    def query(self, cql: str) -> list[dict]:
        body = _long_string(cql) + struct.pack("!HB", CONSISTENCY_QUORUM, 0)
        self._send_frame(OP_QUERY, body)
        opcode, payload = self._read_frame()
        if opcode != OP_RESULT:
            raise CqlError(0x000A, f"unexpected result opcode {opcode}")
        kind = struct.unpack("!I", payload[:4])[0]
        if kind != RESULT_ROWS:
            return []
        return self._parse_rows(payload[4:])

    def _parse_rows(self, b: bytes) -> list[dict]:
        off = 0
        flags, col_count = struct.unpack("!II", b[off:off + 8])
        off += 8
        if flags & 0x0002:  # has_more_pages: paging state blob
            plen = struct.unpack("!i", b[off:off + 4])[0]
            off += 4 + max(plen, 0)
        global_spec = bool(flags & 0x0001)
        if global_spec:
            for _ in range(2):  # keyspace + table
                slen = struct.unpack("!H", b[off:off + 2])[0]
                off += 2 + slen
        cols = []
        for _ in range(col_count):
            if not global_spec:
                for _ in range(2):
                    slen = struct.unpack("!H", b[off:off + 2])[0]
                    off += 2 + slen
            nlen = struct.unpack("!H", b[off:off + 2])[0]
            name = b[off + 2:off + 2 + nlen].decode()
            off += 2 + nlen
            type_id = struct.unpack("!H", b[off:off + 2])[0]
            off += 2
            # custom/parameterized types carry extra payload; only the
            # scalar ids above appear in the YCQL workload tables
            if type_id == 0x0020 or type_id == 0x0022:  # list/set<t>
                off += 2
            elif type_id == 0x0021:  # map<k,v>
                off += 4
            cols.append((name, type_id))
        row_count = struct.unpack("!I", b[off:off + 4])[0]
        off += 4
        rows = []
        for _ in range(row_count):
            row = {}
            for name, type_id in cols:
                vlen = struct.unpack("!i", b[off:off + 4])[0]
                off += 4
                if vlen < 0:
                    row[name] = None
                else:
                    row[name] = _decode_value(type_id, b[off:off + vlen])
                    off += vlen
            rows.append(row)
        return rows

    def close(self) -> None:
        close_quietly(self.sock)
        self.sock = None


# ---------------------------------------------------------------------------
# workload client over one CQLConnection
# ---------------------------------------------------------------------------

from jepsen_tpu.client import Client  # noqa: E402

KEYSPACE = "jepsen"
SET_GROUPS = 8  # ycql/set.clj group-count for the indexed variant


class YCQLSuiteClient(Client):
    """The YCQL half of yugabyte's api split (yugabyte/core.clj:74-85):
    one client speaking every YCQL workload over the from-scratch CQL
    wire protocol — counter/set updates, LWT cas (UPDATE ... IF), and
    single-statement ``BEGIN TRANSACTION ... END TRANSACTION`` batches
    for the transactional workloads (ycql/bank.clj:51-60,
    ycql/multi_key_acid.clj:49-60).

    Error discipline mirrors the SQL family: CqlError on a read fails
    the op; CqlError or a network error on a write is indeterminate
    (info) and the connection is rebuilt before its next use."""

    def __init__(self, port: int = 9042, user: str = "", password: str = "",
                 timeout_s: float = 10.0, node: str | None = None):
        self.port = port
        self.user = user
        self.password = password
        self.timeout_s = timeout_s
        self.node = node
        self.conn: CQLConnection | None = None
        self._broken = False

    def _connect(self, test):
        host = self.node or (test.get("nodes") or ["localhost"])[0]
        self.conn = CQLConnection(host, port=self.port, user=self.user,
                                  password=self.password,
                                  timeout_s=self.timeout_s)

    def open(self, test, node):
        c = type(self)(port=self.port, user=self.user,
                       password=self.password, timeout_s=self.timeout_s,
                       node=node)
        c._connect(test)
        return c

    def setup(self, test):
        q = self.conn.query
        q(f"CREATE KEYSPACE IF NOT EXISTS {KEYSPACE}")
        txn_props = " WITH transactions = {'enabled': true}"
        q(f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.counters "
          f"(id INT PRIMARY KEY, v COUNTER)")
        q(f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.elements "
          f"(val INT PRIMARY KEY, count COUNTER)")
        q(f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.elements_idx "
          f"(key INT PRIMARY KEY, val INT, grp INT){txn_props}")
        q(f"CREATE INDEX IF NOT EXISTS elements_by_group "
          f"ON {KEYSPACE}.elements_idx (grp)")
        q(f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.bank "
          f"(id INT PRIMARY KEY, balance BIGINT){txn_props}")
        q(f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.long_fork "
          f"(key INT PRIMARY KEY, val INT){txn_props}")
        q(f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.single_key_acid "
          f"(id INT PRIMARY KEY, val INT)")
        q(f"CREATE TABLE IF NOT EXISTS {KEYSPACE}.multi_key_acid "
          f"(id INT, ik INT, val INT, PRIMARY KEY (id, ik)){txn_props}")
        for a in test.get("accounts", []):
            q(f"INSERT INTO {KEYSPACE}.bank (id, balance) "
              f"VALUES ({int(a)}, 10) IF NOT EXISTS")

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            except Exception:  # noqa: BLE001
                pass
            self.conn = None

    def teardown(self, test):
        pass

    # -- op dispatch ------------------------------------------------------

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if self._broken:
            self.close(test)
            self._connect(test)
            self._broken = False
        try:
            if test.get("counter") and f == "add":
                self.conn.query(
                    f"UPDATE {KEYSPACE}.counters SET v = v + {int(v)} "
                    f"WHERE id = 0")
                return {**op, "type": "ok"}
            if test.get("counter") and f == "read" and v is None:
                rows = self.conn.query(
                    f"SELECT v FROM {KEYSPACE}.counters WHERE id = 0")
                val = rows[0]["v"] if rows else 0
                return {**op, "type": "ok", "value": int(val or 0)}
            if f == "add" and test.get("set-index"):
                g = int(v) % SET_GROUPS
                self.conn.query(
                    f"INSERT INTO {KEYSPACE}.elements_idx (key, val, grp) "
                    f"VALUES ({int(v)}, {int(v)}, {g})")
                return {**op, "type": "ok"}
            if f == "read" and v is None and test.get("set-index"):
                out = []
                for g in range(SET_GROUPS):  # per-group reads ride the index
                    rows = self.conn.query(
                        f"SELECT val FROM {KEYSPACE}.elements_idx "
                        f"WHERE grp = {g}")
                    out += [r["val"] for r in rows]
                return {**op, "type": "ok", "value": sorted(out)}
            if f == "add":
                self.conn.query(
                    f"UPDATE {KEYSPACE}.elements SET count = count + 1 "
                    f"WHERE val = {int(v)}")
                return {**op, "type": "ok"}
            if f == "read" and v is None and test.get("accounts"):
                return self._read_bank(op, test)
            if f == "read" and v is None:
                rows = self.conn.query(
                    f"SELECT val, count FROM {KEYSPACE}.elements")
                out = []
                for r in rows:  # ycql/set.clj expands count-weighted rows
                    out += [r["val"]] * int(r.get("count") or 0)
                return {**op, "type": "ok", "value": sorted(out)}
            if f == "transfer":
                return self._transfer(op)
            if f == "read" and isinstance(v, (list, tuple)):
                k, _ = v
                rows = self.conn.query(
                    f"SELECT val FROM {KEYSPACE}.single_key_acid "
                    f"WHERE id = {int(k)}")
                val = rows[0]["val"] if rows else None
                return {**op, "type": "ok", "value": [k, val]}
            if f == "write":
                k, val = v
                self.conn.query(
                    f"INSERT INTO {KEYSPACE}.single_key_acid (id, val) "
                    f"VALUES ({int(k)}, {int(val)})")
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                rows = self.conn.query(
                    f"UPDATE {KEYSPACE}.single_key_acid SET val = {int(new)} "
                    f"WHERE id = {int(k)} IF val = {int(old)}")
                applied = bool(rows and rows[0].get("[applied]"))
                return {**op, "type": "ok" if applied else "fail"}
            if f == "txn" and test.get("txn-mode") == "multi":
                return self._multi_txn(op)
            if f == "txn":
                return self._long_fork_txn(op)
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except CqlError as e:
            self._broken = True
            typ = "fail" if f == "read" else "info"
            return {**op, "type": typ, "error": ["cql", e.code, e.message]}
        except (TimeoutError, ConnectionError, OSError) as e:
            self._broken = True
            typ = "fail" if f == "read" else "info"
            return {**op, "type": typ, "error": [type(e).__name__, str(e)]}

    def _transfer(self, op):
        """Balance-guarded two-row transfer in one YCQL transaction
        (ycql/bank.clj:40-60: read the source balance, refuse overdraft,
        then a BEGIN TRANSACTION of two updates)."""
        t = op.get("value") or {}
        frm, to, amount = int(t["from"]), int(t["to"]), int(t["amount"])
        rows = self.conn.query(
            f"SELECT balance FROM {KEYSPACE}.bank WHERE id = {frm}")
        bal = rows[0]["balance"] if rows else None
        if bal is None or bal < amount:
            return {**op, "type": "fail", "error": ["insufficient-funds"]}
        self.conn.query(
            f"BEGIN TRANSACTION "
            f"UPDATE {KEYSPACE}.bank SET balance = balance - {amount} "
            f"WHERE id = {frm}; "
            f"UPDATE {KEYSPACE}.bank SET balance = balance + {amount} "
            f"WHERE id = {to}; "
            f"END TRANSACTION;")
        return {**op, "type": "ok"}

    def _read_bank(self, op, test):
        rows = self.conn.query(
            f"SELECT id, balance FROM {KEYSPACE}.bank")
        return {**op, "type": "ok",
                "value": {r["id"]: r["balance"] for r in rows}}

    def _multi_txn(self, op):
        """Multi-key-acid txn (ycql/multi_key_acid.clj:43-60): writes
        batch into one BEGIN TRANSACTION; reads select the group's rows."""
        k, mops = op.get("value")
        writes = [m for m in mops if m[0] == "w"]
        if writes:
            stmts = "".join(
                f"INSERT INTO {KEYSPACE}.multi_key_acid (id, ik, val) "
                f"VALUES ({int(k)}, {int(ik)}, {int(val)}); "
                for _, ik, val in writes)
            self.conn.query(
                f"BEGIN TRANSACTION {stmts}END TRANSACTION;")
            return {**op, "type": "ok", "value": [k, mops]}
        rows = self.conn.query(
            f"SELECT ik, val FROM {KEYSPACE}.multi_key_acid "
            f"WHERE id = {int(k)}")
        by_ik = {r["ik"]: r["val"] for r in rows}
        filled = [[f2, ik, by_ik.get(ik)] for f2, ik, _ in mops]
        return {**op, "type": "ok", "value": [k, filled]}

    def _long_fork_txn(self, op):
        """Long-fork txns: single-write inserts, whole-group reads
        (ycql/long_fork.clj shape)."""
        mops = op.get("value") or []
        if any(m[0] == "w" for m in mops):
            stmts = "".join(
                f"INSERT INTO {KEYSPACE}.long_fork (key, val) "
                f"VALUES ({int(k)}, {int(val)}); "
                for f2, k, val in mops if f2 == "w")
            self.conn.query(f"BEGIN TRANSACTION {stmts}END TRANSACTION;")
            return {**op, "type": "ok"}
        keys = [int(k) for f2, k, _ in mops if f2 == "r"]
        rows = self.conn.query(
            f"SELECT key, val FROM {KEYSPACE}.long_fork "
            f"WHERE key IN ({', '.join(map(str, keys))})")
        by_key = {r["key"]: r["val"] for r in rows}
        filled = [[f2, k, by_key.get(int(k))] for f2, k, _ in mops]
        return {**op, "type": "ok", "value": filled}
