"""Shared SQL client for the MySQL-protocol suite family — galera,
percona, mysql-cluster, and tidb (reference: the jdbc client layers in
galera/src/jepsen/galera.clj:86-187, percona/src/jepsen/percona.clj,
tidb/src/tidb/{sql,txn}.clj).

One client class speaks every bundled SQL workload over the
from-scratch wire protocol in ``_mysql.py``:

- register r/w/cas: keyed rows with UPDATE-guarded compare-and-set
- set add/read: grow-only table of unique ints (galera.clj:214-236)
- bank read/transfer: serializable two-row transfer with negative-
  balance refusal (galera.clj:260-308)
- dirty-reads read/write: write sets *all* rows of a small table in one
  txn, read scans them (galera/dirty_reads.clj:29-67)
- txn (Elle list-append / rw-register micro-ops): per-key rows appended
  via ``ON DUPLICATE KEY UPDATE CONCAT`` exactly like tidb's mop!
  (tidb/src/tidb/txn.clj:19-48)

Error discipline (galera.clj:133-176): deadlock/lock-wait rollbacks and
galera's "WSREP has not yet prepared node" are definite ``fail``s (the
txn did not commit); network errors fail reads and are indeterminate
``info`` for writes. A connection that errored mid-conversation is
rebuilt before its next use, since leftover response bytes would desync
the wire protocol.
"""
from __future__ import annotations

from jepsen_tpu.client import Client
from jepsen_tpu.suites._mysql import MySQLConnection, MySQLError

# MySQL errnos that mean "transaction rolled back, definitely not applied"
ER_LOCK_DEADLOCK = 1213
ER_LOCK_WAIT_TIMEOUT = 1205
ROLLBACK_ERRNOS = (ER_LOCK_DEADLOCK, ER_LOCK_WAIT_TIMEOUT)


def parse_int_list(text: str | None) -> list[int]:
    """``"1,2,3"`` → ``[1, 2, 3]`` (the CONCAT-encoded list rows)."""
    if not text:
        return []
    return [int(x) for x in text.split(",") if x != ""]


def create_db_and_user(db_name: str, user: str, password: str,
                       root_pass: str | None = None,
                       port: int | None = None) -> None:
    """Creates the jepsen database and a ``'%'``-visible user via the
    node-local mysql shell (galera.clj:95-100) — shared by every
    MySQL-family suite's DB automation."""
    from jepsen_tpu import control
    argv = ["mysql", "-u", "root"]
    if root_pass:
        argv.append(f"--password={root_pass}")
    if port:
        argv += ["-h", "127.0.0.1", "-P", str(port)]
    for sql in (f"CREATE DATABASE IF NOT EXISTS {db_name};",
                f"CREATE USER IF NOT EXISTS '{user}'@'%' "
                f"IDENTIFIED BY '{password}';",
                f"GRANT ALL PRIVILEGES ON {db_name}.* TO '{user}'@'%';"):
        control.exec_(*argv, "-e", sql)


class MySQLSuiteClient(Client):
    """Workload client over one MySQLConnection. ``engine`` appends an
    ENGINE clause to CREATE TABLE (mysql-cluster needs NDBCLUSTER);
    ``endpoint_mode`` is "node" (connect to your own node — the
    multi-primary galera/percona/tidb shape) or "first" (all clients
    share node 1)."""

    def __init__(self, *, port: int = 3306, database: str = "jepsen",
                 user: str = "jepsen", password: str = "jepsen",
                 isolation: str = "serializable", engine: str | None = None,
                 endpoint_mode: str = "node", txn_style: str = "append",
                 timeout_s: float = 10.0, node: str | None = None):
        self.port = port
        self.database = database
        self.user = user
        self.password = password
        self.isolation = isolation
        self.engine = engine
        self.endpoint_mode = endpoint_mode
        # "append": txn r micro-ops read the lists table (Elle
        # list-append); "wr": they read registers (Elle rw-register)
        self.txn_style = txn_style
        self.timeout_s = timeout_s
        self.node = node
        self.conn: MySQLConnection | None = None
        self._broken = False

    # -- lifecycle --------------------------------------------------------

    def endpoint(self, test, node) -> str:
        if self.endpoint_mode == "first":
            return (test.get("nodes") or [node])[0]
        return node

    def _connect(self, test):
        self.conn = MySQLConnection(
            self.endpoint(test, self.node), port=self.port, user=self.user,
            password=self.password, database=self.database,
            timeout_s=self.timeout_s)
        # session isolation is sticky — set once per connection, not per txn
        level = self.isolation.upper().replace("-", " ")
        self.conn.query(
            f"SET SESSION TRANSACTION ISOLATION LEVEL {level}")

    def open(self, test, node):
        c = type(self)(port=self.port, database=self.database,
                       user=self.user, password=self.password,
                       isolation=self.isolation, engine=self.engine,
                       endpoint_mode=self.endpoint_mode,
                       txn_style=self.txn_style,
                       timeout_s=self.timeout_s, node=node)
        c._connect(test)
        return c

    def setup(self, test):
        suffix = f" ENGINE={self.engine}" if self.engine else ""
        for ddl in (
                "CREATE TABLE IF NOT EXISTS registers "
                f"(k INT NOT NULL PRIMARY KEY, v BIGINT){suffix}",
                "CREATE TABLE IF NOT EXISTS sets "
                f"(elem BIGINT NOT NULL PRIMARY KEY){suffix}",
                "CREATE TABLE IF NOT EXISTS accounts "
                f"(id INT NOT NULL PRIMARY KEY, balance BIGINT NOT NULL)"
                f"{suffix}",
                "CREATE TABLE IF NOT EXISTS dirty "
                f"(id INT NOT NULL PRIMARY KEY, x BIGINT NOT NULL){suffix}",
                "CREATE TABLE IF NOT EXISTS lists "
                f"(k INT NOT NULL PRIMARY KEY, elems TEXT){suffix}"):
            self.conn.query(ddl)
        if test.get("set-cas"):
            # tidb/sets.clj CasSetClient: the whole set is one text row
            self.conn.query("CREATE TABLE IF NOT EXISTS sets_cas "
                            f"(id INT NOT NULL PRIMARY KEY, value TEXT)"
                            f"{suffix}")
        if test.get("monotonic-key"):
            # tidb/monotonic.clj:44-49: the increment-only key pool
            self.conn.query(
                "CREATE TABLE IF NOT EXISTS cycle "
                "(pk INT NOT NULL PRIMARY KEY, sk INT NOT NULL, val INT)"
                f"{suffix}")
        if test.get("key-count"):
            # tidb/sequential.clj:32-61: subkeys split across tables so
            # they land in different shard ranges
            from jepsen_tpu.suites._pg_client import SEQ_TABLE_COUNT
            for i in range(SEQ_TABLE_COUNT):
                self.conn.query(
                    f"CREATE TABLE IF NOT EXISTS seq_{i} "
                    f"(k VARCHAR(191) NOT NULL PRIMARY KEY){suffix}")
        if test.get("bank-multitable"):
            # tidb/bank.clj MultiBankClient: one table per account
            accounts = list(test.get("accounts", []))
            total = int(test.get("total-amount", 10 * len(accounts) or 80))
            for i, a in enumerate(accounts):
                self.conn.query(
                    f"CREATE TABLE IF NOT EXISTS accounts{int(a)} "
                    f"(id INT NOT NULL PRIMARY KEY, balance BIGINT NOT "
                    f"NULL){suffix}")
                self.conn.query(
                    f"INSERT IGNORE INTO accounts{int(a)} (id, balance) "
                    f"VALUES (0, {total if i == 0 else 0})")
        # bank initial balances (galera.clj:262-273) and dirty rows
        # (dirty_reads.clj:31-43); both idempotent across clients
        for a in ([] if test.get("bank-multitable")
                  else test.get("accounts", [])):
            self.conn.query(
                f"INSERT IGNORE INTO accounts (id, balance) "
                f"VALUES ({int(a)}, 10)")
        for i in range(int(test.get("dirty-rows", 0) or 0)):
            self.conn.query(
                f"INSERT IGNORE INTO dirty (id, x) VALUES ({int(i)}, -1)")

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            except Exception:  # noqa: BLE001
                pass

    # -- transactions -----------------------------------------------------

    def _begin(self):
        self.conn.query("BEGIN")

    def _rollback(self):
        try:
            self.conn.query("ROLLBACK")
        except (MySQLError, OSError):
            self._broken = True

    def _select_int(self, sql: str):
        rows = self.conn.query(sql)
        if not rows or rows[0][0] is None:
            return None
        return int(rows[0][0])

    # -- op dispatch ------------------------------------------------------

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if self._broken:
            self.close(test)
            self._connect(test)
            self._broken = False
        try:
            if test.get("table-workload") and f == "create-table":
                self.conn.query(
                    f"CREATE TABLE IF NOT EXISTS t{int(v)} "
                    f"(id INT NOT NULL PRIMARY KEY, val INT)")
                return {**op, "type": "ok"}
            if test.get("table-workload") and f == "insert":
                tid, k = v
                try:
                    self.conn.query(
                        f"INSERT INTO t{int(tid)} (id, val) "
                        f"VALUES ({int(k)}, 0)")
                except MySQLError as e:
                    if "doesn't exist" in e.msg or e.code == 1146:
                        return {**op, "type": "fail",
                                "error": ["doesnt-exist", tid]}
                    if e.code == 1062:  # duplicate key: insert still proves
                        #                 the table is visible
                        return {**op, "type": "fail",
                                "error": ["duplicate-key", tid]}
                    raise
                return {**op, "type": "ok"}
            if test.get("set-cas") and f == "add":
                return self._cas_set_add(op)
            if test.get("set-cas") and f == "read" and v is None:
                return self._cas_set_read(op)
            if test.get("bank-multitable") and f == "transfer":
                return self._multitable_transfer(test, op)
            if test.get("bank-multitable") and f == "read" and v is None:
                return self._multitable_read(test, op)
            if test.get("monotonic-key") and f == "inc":
                return self._mono_key_inc(op)
            if test.get("monotonic-key") and f == "read":
                return self._mono_key_read(op)
            if test.get("key-count") and f == "write":
                return self._seq_write(test, op)
            if test.get("key-count") and f == "read":
                return self._seq_read(test, op)
            if f == "read" and v is None:
                return self._whole_read(test, op)
            if f == "read":
                k, _ = v
                val = self._select_int(
                    f"SELECT v FROM registers WHERE k = {int(k)}")
                return {**op, "type": "ok", "value": [k, val]}
            if f == "write" and isinstance(v, (list, tuple)):
                k, val = v
                self.conn.query(
                    f"INSERT INTO registers (k, v) VALUES ({int(k)}, "
                    f"{int(val)}) ON DUPLICATE KEY UPDATE v = {int(val)}")
                return {**op, "type": "ok"}
            if f == "write":
                return self._dirty_write(test, op)
            if f == "cas":
                k, (old, new) = v
                affected, _ = self.conn.query(
                    f"UPDATE registers SET v = {int(new)} "
                    f"WHERE k = {int(k)} AND v = {int(old)}")
                return {**op, "type": "ok" if affected == 1 else "fail"}
            if f == "add":
                self.conn.query(
                    f"INSERT IGNORE INTO sets (elem) VALUES ({int(v)})")
                return {**op, "type": "ok"}
            if f == "transfer":
                return self._transfer(op)
            if f == "txn":
                return self._txn(op)
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except MySQLError as e:
            return self._sql_error(op, e)
        except (TimeoutError, ConnectionError, OSError) as e:
            self._broken = True
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def _sql_error(self, op, e: MySQLError):
        if e.code in ROLLBACK_ERRNOS:
            return {**op, "type": "fail", "error": ["rollback", e.msg]}
        if "WSREP has not yet prepared node" in e.msg:
            # galera node not in the primary component (galera.clj:167-176)
            return {**op, "type": "fail", "error": ["wsrep", e.msg]}
        # unknown server error after a possible partial conversation:
        # reads are safe to fail; writes are indeterminate
        kind = "fail" if op.get("f") == "read" else "info"
        return {**op, "type": kind, "error": ["sql", e.code, e.msg]}

    # -- workload bodies --------------------------------------------------

    def _whole_read(self, test, op):
        """A bare read: bank balances when the test carries accounts,
        dirty rows when it carries dirty-rows, else the whole set."""
        if test.get("accounts"):
            rows = self.conn.query(
                "SELECT id, balance FROM accounts ORDER BY id")
            return {**op, "type": "ok",
                    "value": {int(r[0]): int(r[1]) for r in rows}}
        if test.get("dirty-rows"):
            rows = self.conn.query("SELECT x FROM dirty ORDER BY id")
            return {**op, "type": "ok",
                    "value": [int(r[0]) for r in rows]}
        rows = self.conn.query("SELECT elem FROM sets ORDER BY elem")
        return {**op, "type": "ok", "value": [int(r[0]) for r in rows]}

    @staticmethod
    def _acct_loc(a):
        """(table, where) for the shared single accounts table."""
        return "accounts", f"id = {int(a)}"

    @staticmethod
    def _acct_loc_multi(a):
        """(table, where) for per-account tables (tidb/bank.clj
        MultiBankClient)."""
        return f"accounts{int(a)}", "id = 0"

    def _transfer(self, op, loc=None):
        """Two-row serializable transfer (galera.clj:277-306): read both
        balances, refuse overdrafts, write both. ``loc(account) ->
        (table, where)`` picks the storage layout."""
        loc = loc or self._acct_loc
        t = op.get("value") or {}
        frm, to = int(t.get("from")), int(t.get("to"))
        amount = int(t.get("amount", 0))
        (ft, fw), (tt, tw) = loc(frm), loc(to)
        self._begin()
        try:
            b1 = self._select_int(f"SELECT balance FROM {ft} WHERE {fw}")
            b2 = self._select_int(f"SELECT balance FROM {tt} WHERE {tw}")
            if b1 is None or b2 is None:
                self._rollback()
                return {**op, "type": "fail", "error": ["no-such-account"]}
            if b1 - amount < 0:
                self._rollback()
                return {**op, "type": "fail",
                        "error": ["negative", frm, b1 - amount]}
            self.conn.query(f"UPDATE {ft} SET balance = {b1 - amount} "
                            f"WHERE {fw}")
            self.conn.query(f"UPDATE {tt} SET balance = {b2 + amount} "
                            f"WHERE {tw}")
            self.conn.query("COMMIT")
            return {**op, "type": "ok"}
        except MySQLError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _mono_key_inc(self, op):
        """One r/w txn bumping a key (tidb/monotonic.clj:57-83): read
        the value, insert 0 when absent, else write v+1; the ok value is
        what was written."""
        k = int(op.get("value"))
        self._begin()
        try:
            v = self._select_int(f"SELECT val FROM cycle WHERE pk = {k}")
            if v is None:
                self.conn.query(
                    f"INSERT INTO cycle (pk, sk, val) VALUES ({k}, {k}, 0)")
                written = 0
            else:
                self.conn.query(
                    f"UPDATE cycle SET val = {v + 1} WHERE pk = {k}")
                written = v + 1
            self.conn.query("COMMIT")
            return {**op, "type": "ok", "value": {k: written}}
        except MySQLError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _mono_key_read(self, op):
        """Snapshot the key pool in one txn, shuffled read order, -1 for
        missing keys (tidb/monotonic.clj:19-33,54-56)."""
        import random as _random
        ks = list((op.get("value") or {}).keys())
        _random.shuffle(ks)
        self._begin()
        try:
            out = {}
            for k in ks:
                v = self._select_int(
                    f"SELECT val FROM cycle WHERE pk = {int(k)}")
                out[k] = -1 if v is None else v
            self.conn.query("COMMIT")
            return {**op, "type": "ok",
                    "value": dict(sorted(out.items()))}
        except MySQLError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _seq_write(self, test, op):
        """Insert a key's subkeys in order, one txn each
        (tidb/sequential.clj:63-71)."""
        from jepsen_tpu.suites._pg_client import seq_table
        from jepsen_tpu.workloads.sequential import subkeys
        for sk in subkeys(int(test.get("key-count", 5)), op.get("value")):
            self.conn.query(
                f"INSERT IGNORE INTO {seq_table(sk)} (k) VALUES ('{sk}')")
        return {**op, "type": "ok"}

    def _seq_read(self, test, op):
        """Read subkeys reversed (tidb/sequential.clj:73-85)."""
        from jepsen_tpu.suites._pg_client import seq_table
        from jepsen_tpu.workloads.sequential import subkeys
        ks = subkeys(int(test.get("key-count", 5)), op.get("value"))
        out = []
        for sk in reversed(ks):
            rows = self.conn.query(
                f"SELECT k FROM {seq_table(sk)} WHERE k = '{sk}'")
            out.append(rows[0][0] if rows else None)
        return {**op, "type": "ok", "value": [op.get("value"), out]}

    def _cas_set_add(self, op):
        """Append to the single text-row set under a txn
        (tidb/sets.clj CasSetClient :add) — the read-modify-write
        contention probe the plain insert-per-element set can't be."""
        e = int(op.get("value"))
        self._begin()
        try:
            rows = self.conn.query("SELECT value FROM sets_cas WHERE id = 0")
            if rows and rows[0][0] not in (None, ""):
                self.conn.query(
                    f"UPDATE sets_cas SET value = CONCAT(value, ',{e}') "
                    f"WHERE id = 0")
            else:
                # the empty read may race a concurrent first insert: the
                # duplicate-key fallback must APPEND, never overwrite, or
                # an acknowledged element vanishes and the set checker
                # wrongly convicts the DB
                self.conn.query(
                    f"INSERT INTO sets_cas (id, value) VALUES (0, '{e}') "
                    f"ON DUPLICATE KEY UPDATE value = IF(value IS NULL OR "
                    f"value = '', '{e}', CONCAT(value, ',{e}'))")
            self.conn.query("COMMIT")
            return {**op, "type": "ok"}
        except MySQLError as e2:
            self._rollback()
            return self._sql_error(op, e2)

    def _cas_set_read(self, op):
        rows = self.conn.query("SELECT value FROM sets_cas WHERE id = 0")
        raw = rows[0][0] if rows else None
        return {**op, "type": "ok", "value": sorted(parse_int_list(raw))}

    def _multitable_transfer(self, test, op):
        """Per-account-table transfer (tidb/bank.clj MultiBankClient):
        _transfer's discipline with the per-table layout."""
        return self._transfer(op, loc=self._acct_loc_multi)

    def _multitable_read(self, test, op):
        self._begin()
        try:
            out = {}
            for a in test.get("accounts", []):
                t, w = self._acct_loc_multi(a)
                out[int(a)] = self._select_int(
                    f"SELECT balance FROM {t} WHERE {w}")
            self.conn.query("COMMIT")
            return {**op, "type": "ok", "value": out}
        except MySQLError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _dirty_write(self, test, op):
        """Set every dirty row to op value in one txn
        (dirty_reads.clj:59-65): select each row, then update each."""
        x = int(op.get("value"))
        n = int(test.get("dirty-rows", 4) or 4)
        self._begin()
        try:
            for i in range(n):
                self.conn.query(f"SELECT x FROM dirty WHERE id = {i}")
            for i in range(n):
                self.conn.query(f"UPDATE dirty SET x = {x} WHERE id = {i}")
            self.conn.query("COMMIT")
            return {**op, "type": "ok"}
        except MySQLError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _txn(self, op):
        """Elle micro-op transaction (tidb/src/tidb/txn.clj:19-48):
        r → SELECT, append → CONCAT upsert, w → plain upsert."""
        self._begin()
        out = []
        try:
            for f, k, v in op.get("value") or []:
                if f == "r" and self.txn_style == "wr":
                    val = self._select_int(
                        f"SELECT v FROM registers WHERE k = {int(k)}")
                    out.append(["r", k, val])
                elif f == "r":
                    rows = self.conn.query(
                        f"SELECT elems FROM lists WHERE k = {int(k)}")
                    out.append(["r", k,
                                parse_int_list(rows[0][0]) if rows else []])
                elif f == "append":
                    self.conn.query(
                        f"INSERT INTO lists (k, elems) VALUES ({int(k)}, "
                        f"'{int(v)}') ON DUPLICATE KEY UPDATE "
                        f"elems = CONCAT(elems, ',', '{int(v)}')")
                    out.append(["append", k, v])
                elif f == "w":
                    self.conn.query(
                        f"INSERT INTO registers (k, v) VALUES ({int(k)}, "
                        f"{int(v)}) ON DUPLICATE KEY UPDATE v = {int(v)}")
                    out.append(["w", k, v])
                else:
                    raise ValueError(f"unknown micro-op {f!r}")
            self.conn.query("COMMIT")
            return {**op, "type": "ok", "value": out}
        except MySQLError as e:
            self._rollback()
            return self._sql_error(op, e)
