"""etcd test suite — the tutorial workload (reference: doc/tutorial/
01-scaffolding.md..08, jepsen/src/jepsen/tests/linearizable_register.clj;
BASELINE config 1: etcd single-register r/w/cas history).

DB automation installs an etcd release tarball on each node (cached on the
control node, control/util.clj install-archive! pattern), starts it as a
daemon with a static initial cluster, and wipes data on teardown. The
client speaks etcd's v2 keys HTTP API with stdlib urllib (the reference
tutorial's Verschlimmbesserung client is exactly this API), mapping
network timeouts on writes/cas to indeterminate ``info`` ops.

``--fake`` swaps in the in-memory atom client/DB over the dummy remote
(tests.clj:27-67 pattern), so the full suite lifecycle runs with no
cluster — the tier-2 test strategy of SURVEY.md §4.
"""
from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.parse
import urllib.request

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)

logger = logging.getLogger("jepsen.etcd")

DEFAULT_VERSION = "3.5.15"
DIR = "/opt/etcd"
DATA_DIR = f"{DIR}/data"
LOG_FILE = f"{DIR}/etcd.log"
PIDFILE = f"{DIR}/etcd.pid"
CLIENT_PORT = 2379
PEER_PORT = 2380


def archive_url(version: str) -> str:
    return (f"https://github.com/etcd-io/etcd/releases/download/"
            f"v{version}/etcd-v{version}-linux-amd64.tar.gz")


def node_url(node: str, port: int) -> str:
    return f"http://{node}:{port}"


def initial_cluster(test: dict) -> str:
    """node=peer-url pairs (tutorial 02-db.md's initial-cluster string)."""
    return ",".join(f"{n}={node_url(n, PEER_PORT)}"
                    for n in test.get("nodes") or [])


class EtcdDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.Primary,
             db_mod.LogFiles):
    """etcd lifecycle automation (tutorial 02-db.md; db.clj protocols)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing etcd %s", node, self.version)
        cu.install_archive(archive_url(self.version), DIR)
        self.start(test, node)
        cu.await_tcp_port(CLIENT_PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DATA_DIR)
        cu.rm_rf(LOG_FILE)

    # db_mod.Process
    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/etcd",
            "--name", node,
            "--data-dir", DATA_DIR,
            "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
            "--advertise-client-urls", node_url(node, CLIENT_PORT),
            "--listen-peer-urls", f"http://0.0.0.0:{PEER_PORT}",
            "--initial-advertise-peer-urls", node_url(node, PEER_PORT),
            "--initial-cluster", initial_cluster(test),
            "--initial-cluster-state", "new",
            "--enable-v2",
        )

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/etcd", PIDFILE)
        cu.grepkill("etcd")

    # db_mod.Pause
    def pause(self, test, node):
        cu.grepkill("etcd", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("etcd", sig="CONT")

    # db_mod.Primary — etcd elects its own leader; treat node 1 as the
    # bootstrap primary for setup purposes (db.clj:141-146 semantics).
    def primaries(self, test):
        nodes = test.get("nodes") or []
        return nodes[:1]

    def setup_primary(self, test, node):
        pass

    # db_mod.LogFiles
    def log_files(self, test, node):
        return [LOG_FILE]


class EtcdClient(Client):
    """r/w/cas registers + set adds over etcd's v2 keys API.

    Register ops arrive independent-lifted with ``[k, v]`` tuple values
    (independent.clj:21-29) — the key names the etcd key, exactly as the
    reference tutorial's client destructures ``(:value op)``
    (doc/tutorial/07-parameters.md). Set ops (``add``, whole-set
    ``read``) map to a key directory. Linearizable reads use
    ``quorum=true``. Timeouts and connection errors on mutating ops
    complete as ``info`` (the op may or may not have applied —
    interpreter.clj:142-157 semantics); reads may safely ``fail``.
    """

    def __init__(self, prefix: str = "jepsen", timeout_s: float = 5.0,
                 node: str | None = None):
        self.prefix = prefix
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return EtcdClient(self.prefix, self.timeout_s, node)

    def _url(self, path: str, **params) -> str:
        q = f"?{urllib.parse.urlencode(params)}" if params else ""
        return (f"{node_url(self.node, CLIENT_PORT)}/v2/keys/"
                f"{urllib.parse.quote(path)}{q}")

    def _request(self, url: str, data: dict | None = None,
                 method: str = "GET") -> dict:
        body = urllib.parse.urlencode(data).encode() if data else None
        req = urllib.request.Request(url, data=body, method=method)
        if body:
            req.add_header("Content-Type", "application/x-www-form-urlencoded")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode())

    def _read_register(self, k):
        try:
            doc = self._request(self._url(f"{self.prefix}/{k}", quorum="true"))
            return int(doc["node"]["value"])
        except urllib.error.HTTPError as e:
            if e.code == 404:  # key not yet written
                return None
            raise

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "add":
                self._request(self._url(f"{self.prefix}-set/{v}"),
                              {"value": str(v)}, method="PUT")
                return {**op, "type": "ok"}
            if f == "read" and v is None:  # whole-set read
                try:
                    doc = self._request(self._url(f"{self.prefix}-set",
                                                  recursive="true",
                                                  quorum="true"))
                    nodes = (doc.get("node") or {}).get("nodes") or []
                    elems = sorted(int(n["key"].rsplit("/", 1)[-1])
                                   for n in nodes)
                except urllib.error.HTTPError as e:
                    if e.code != 404:
                        raise
                    elems = []
                return {**op, "type": "ok", "value": elems}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self._read_register(k)]}
            if f == "write":
                k, val = v
                self._request(self._url(f"{self.prefix}/{k}"),
                              {"value": str(val)}, method="PUT")
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                try:
                    self._request(self._url(f"{self.prefix}/{k}",
                                            prevValue=str(old)),
                                  {"value": str(new)}, method="PUT")
                    return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    # 412 = compare failed, 404 = key not yet written —
                    # both definite no-ops (the tutorial client maps
                    # key-not-found cas to :fail too)
                    if e.code in (412, 404):
                        return {**op, "type": "fail"}
                    raise
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            # 5xx is expected during faults (raft internal error / leader
            # election) — indeterminate for mutations, safe fail for reads.
            # Anything else HTTP-level (unhandled 4xx) is a real bug (wrong
            # API, misconfiguration) — surface it rather than logging noise.
            if e.code >= 500:
                kind = "fail" if f == "read" else "info"
                return {**op, "type": kind, "error": ["http", e.code]}
            raise
        except (TimeoutError, urllib.error.URLError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


# ---------------------------------------------------------------------------
# Membership: grow/shrink/rolling-restart via the v2 members API
# (nemesis/membership.py State protocol; doc/robustness.md)
# ---------------------------------------------------------------------------

def _members_request(node: str, method: str = "GET",
                     body: dict | None = None,
                     member_id: str | None = None,
                     timeout_s: float = 5.0) -> dict:
    """One v2 members-API call against ``node``. Module-level so tests
    (and only tests) can stub the transport without a cluster."""
    url = f"{node_url(node, CLIENT_PORT)}/v2/members"
    if member_id:
        url += f"/{urllib.parse.quote(member_id)}"
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        raw = resp.read().decode()
        return json.loads(raw) if raw.strip() else {}


def _live_members(test: dict) -> tuple[str, list[dict]]:
    """(queried-node, member rows) from the first reachable node."""
    last: Exception | None = None
    for node in test.get("nodes") or []:
        try:
            doc = _members_request(node)
            return node, list(doc.get("members") or [])
        except Exception as e:  # noqa: BLE001 — try the next node
            last = e
    raise RuntimeError(f"no node answered the members API: {last!r}")


def restore_members(test: dict, row: dict) -> None:
    """The etcd membership heal target (``{"mechanism": "import"}`` —
    dispatched by nemesis/membership.heal_record, including offline from
    ``cli heal``): diffs the live member set against the record's
    pre-op set, re-adds removed members and removes half-added ones.
    Idempotent: a member already present answers 409 on add, already
    gone answers 404 on delete — both fine."""
    v = row.get("value") if isinstance(row.get("value"), dict) else {}
    pre = v.get("pre_members")
    if pre is None:
        from jepsen_tpu.nemesis.faults import Unhealable
        raise Unhealable(
            f"membership record {row.get('id')} carries no pre-op "
            "member set")
    via, members = _live_members(test)
    current = {m.get("name"): m for m in members if m.get("name")}
    for name in sorted(set(pre) - set(current)):
        try:
            _members_request(via, method="POST",
                            body={"name": name,
                                  "peerURLs": [node_url(name, PEER_PORT)]})
            logger.info("membership heal: re-added %s", name)
        except urllib.error.HTTPError as e:
            if e.code != 409:  # already a member: the heal is a no-op
                raise
    for name in sorted(set(current) - set(pre)):
        try:
            _members_request(via, method="DELETE",
                            member_id=str(current[name].get("id")))
            logger.info("membership heal: removed half-added %s", name)
        except urllib.error.HTTPError as e:
            if e.code != 404:  # already gone
                raise


class EtcdMembershipState:
    """Membership State over etcd's members API (nemesis/membership.py
    protocol): node views poll ``GET /v2/members``, ops add/remove
    members (plus a rolling restart through the db Process protocol),
    and an op resolves once every polled view agrees with the post-op
    member set. ``merge_views``/``op``/``resolve_op`` are pure model
    logic under the nemesis lock; ``node_view``/``invoke`` do HTTP."""

    def __init__(self, min_members: int | None = None,
                 timeout_s: float = 5.0):
        self.min_members = min_members
        self.timeout_s = timeout_s
        self._lock = threading.Lock()
        self._members: set | None = None   # merged authoritative names
        self._views: dict = {}
        self._inflight: tuple | None = None

    def fs(self):
        return {"add-node", "remove-node", "rolling-restart"}

    def heal_spec(self, test):
        return {"mechanism": "import",
                "module": "jepsen_tpu.suites.etcd", "fn": "restore_members"}

    def node_view(self, test, node):
        doc = _members_request(node, timeout_s=self.timeout_s)
        return sorted(m.get("name") for m in doc.get("members") or ()
                      if m.get("name"))

    def merge_views(self, test, views):
        good = {n: v for n, v in views.items() if v}
        with self._lock:
            self._views = good
            if good:
                # authoritative = the view the most nodes agree on
                tallies: dict = {}
                for v in good.values():
                    tallies[tuple(v)] = tallies.get(tuple(v), 0) + 1
                best = max(tallies.items(), key=lambda kv: kv[1])[0]
                self._members = set(best)
        return self

    def members(self):
        with self._lock:
            return set(self._members) if self._members is not None else None

    def op(self, test):
        from jepsen_tpu.utils import majority
        all_nodes = list(test.get("nodes") or [])
        floor = self.min_members or majority(len(all_nodes))
        with self._lock:
            if self._inflight is not None or self._members is None:
                return "pending"
            absent = sorted(set(all_nodes) - self._members)
            if absent:
                return {"type": "info", "f": "add-node", "value": absent[0]}
            if len(self._members) > floor:
                return {"type": "info", "f": "remove-node",
                        "value": sorted(self._members)[-1]}
        return "pending"

    def invoke(self, test, op):
        f, node = op.get("f"), op.get("value")
        if f == "remove-node":
            via, members = _live_members(test)
            target = next((m for m in members if m.get("name") == node),
                          None)
            if target is None:
                return ["not-a-member", node]
            _members_request(via, method="DELETE",
                            member_id=str(target.get("id")))
            db = test.get("db")
            if isinstance(db, db_mod.Process):
                db.kill(test, node)
            expect_present = False
        elif f == "add-node":
            via, _members = _live_members(test)
            try:
                _members_request(
                    via, method="POST",
                    body={"name": node,
                          "peerURLs": [node_url(node, PEER_PORT)]})
            except urllib.error.HTTPError as e:
                if e.code != 409:  # already a member
                    raise
            db = test.get("db")
            if isinstance(db, db_mod.Process):
                db.start(test, node)
            expect_present = True
        elif f == "rolling-restart":
            db = test.get("db")
            if not isinstance(db, db_mod.Process):
                return ["no-process-protocol"]
            with self._lock:
                members = sorted(self._members or ())
            for n in members or list(test.get("nodes") or []):
                db.kill(test, n)
                db.start(test, n)
                cu.await_tcp_port(CLIENT_PORT, host=n)
            expect_present = None
        else:
            return ["unknown-f", f]
        with self._lock:
            self._inflight = (f, node)
        return {"action": f, "node": node, "expect_present": expect_present}

    def resolve(self, test):
        return self

    def resolve_op(self, test, pending_pair):
        _op, value = pending_pair
        if not isinstance(value, dict):
            # definite no-op (unknown member, unsupported f): resolved
            with self._lock:
                self._inflight = None
            return self
        expect = value.get("expect_present")
        node = value.get("node")
        with self._lock:
            views = dict(self._views)
            if expect is False:
                # the removed node's process was killed: its poll only
                # fails from here on and the nemesis keeps its LAST
                # GOOD view — which still lists the node itself.
                # Requiring that view to agree would block resolution
                # forever; only the surviving members' views count.
                views.pop(node, None)
            if not views:
                return None
            for view in views.values():
                present = node in view
                if expect is not None and present is not expect:
                    return None
            if expect is None:  # rolling restart: views just need accord
                if len({tuple(v) for v in views.values()}) != 1:
                    return None
            self._inflight = None
        return self

    def teardown(self, test):
        pass


def _nemesis_opts(o: dict, base: dict) -> dict:
    """Membership + clock-rate wiring for the combined packages: fake
    mode models the cluster as a durable members file under the store
    dir (SIGKILL-survivable — the chaos lane's heal target); real mode
    drives the etcd members API. The clock-rate binary is the etcd
    binary itself."""
    def state_fn(_pkg_opts):
        if (base.get("ssh") or {}).get("dummy"):
            from pathlib import Path

            from jepsen_tpu.fakes import FakeClusterState
            path = Path(base.get("store_dir", "store")) / \
                f"{base.get('name', 'etcd')}-members.json"
            return FakeClusterState(path, nodes=base.get("nodes"),
                                    settle_s=o.get("membership_settle_s",
                                                   0.5))
        return EtcdMembershipState()

    return {"membership_state_fn": state_fn,
            "clock_rate_binary": f"{DIR}/etcd"}


SUPPORTED_WORKLOADS = ("register", "set")

MEMBERSHIP_FAULTS = ("membership", "clock-rate",
                     "partition-during-reconfig",
                     "clock-rate-during-reconfig")


def etcd_test(opts_dict: dict | None = None) -> dict:
    """Test-map constructor (the zookeeper.clj:105-137 shape)."""
    return build_suite_test(
        opts_dict, db_name="etcd", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": EtcdDB(o.get("version", DEFAULT_VERSION)),
                             "client": EtcdClient(), "os": Debian()},
        nemesis_opts=_nemesis_opts)


main = cli.single_test_cmd(
    standard_test_fn(etcd_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra_faults=MEMBERSHIP_FAULTS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-etcd")


if __name__ == "__main__":
    import sys
    sys.exit(main())
