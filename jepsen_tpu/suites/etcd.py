"""etcd test suite — the tutorial workload (reference: doc/tutorial/
01-scaffolding.md..08, jepsen/src/jepsen/tests/linearizable_register.clj;
BASELINE config 1: etcd single-register r/w/cas history).

DB automation installs an etcd release tarball on each node (cached on the
control node, control/util.clj install-archive! pattern), starts it as a
daemon with a static initial cluster, and wipes data on teardown. The
client speaks etcd's v2 keys HTTP API with stdlib urllib (the reference
tutorial's Verschlimmbesserung client is exactly this API), mapping
network timeouts on writes/cas to indeterminate ``info`` ops.

``--fake`` swaps in the in-memory atom client/DB over the dummy remote
(tests.clj:27-67 pattern), so the full suite lifecycle runs with no
cluster — the tier-2 test strategy of SURVEY.md §4.
"""
from __future__ import annotations

import json
import logging
import urllib.error
import urllib.parse
import urllib.request

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)

logger = logging.getLogger("jepsen.etcd")

DEFAULT_VERSION = "3.5.15"
DIR = "/opt/etcd"
DATA_DIR = f"{DIR}/data"
LOG_FILE = f"{DIR}/etcd.log"
PIDFILE = f"{DIR}/etcd.pid"
CLIENT_PORT = 2379
PEER_PORT = 2380


def archive_url(version: str) -> str:
    return (f"https://github.com/etcd-io/etcd/releases/download/"
            f"v{version}/etcd-v{version}-linux-amd64.tar.gz")


def node_url(node: str, port: int) -> str:
    return f"http://{node}:{port}"


def initial_cluster(test: dict) -> str:
    """node=peer-url pairs (tutorial 02-db.md's initial-cluster string)."""
    return ",".join(f"{n}={node_url(n, PEER_PORT)}"
                    for n in test.get("nodes") or [])


class EtcdDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.Primary,
             db_mod.LogFiles):
    """etcd lifecycle automation (tutorial 02-db.md; db.clj protocols)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing etcd %s", node, self.version)
        cu.install_archive(archive_url(self.version), DIR)
        self.start(test, node)
        cu.await_tcp_port(CLIENT_PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DATA_DIR)
        cu.rm_rf(LOG_FILE)

    # db_mod.Process
    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/etcd",
            "--name", node,
            "--data-dir", DATA_DIR,
            "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
            "--advertise-client-urls", node_url(node, CLIENT_PORT),
            "--listen-peer-urls", f"http://0.0.0.0:{PEER_PORT}",
            "--initial-advertise-peer-urls", node_url(node, PEER_PORT),
            "--initial-cluster", initial_cluster(test),
            "--initial-cluster-state", "new",
            "--enable-v2",
        )

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/etcd", PIDFILE)
        cu.grepkill("etcd")

    # db_mod.Pause
    def pause(self, test, node):
        cu.grepkill("etcd", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("etcd", sig="CONT")

    # db_mod.Primary — etcd elects its own leader; treat node 1 as the
    # bootstrap primary for setup purposes (db.clj:141-146 semantics).
    def primaries(self, test):
        nodes = test.get("nodes") or []
        return nodes[:1]

    def setup_primary(self, test, node):
        pass

    # db_mod.LogFiles
    def log_files(self, test, node):
        return [LOG_FILE]


class EtcdClient(Client):
    """r/w/cas registers + set adds over etcd's v2 keys API.

    Register ops arrive independent-lifted with ``[k, v]`` tuple values
    (independent.clj:21-29) — the key names the etcd key, exactly as the
    reference tutorial's client destructures ``(:value op)``
    (doc/tutorial/07-parameters.md). Set ops (``add``, whole-set
    ``read``) map to a key directory. Linearizable reads use
    ``quorum=true``. Timeouts and connection errors on mutating ops
    complete as ``info`` (the op may or may not have applied —
    interpreter.clj:142-157 semantics); reads may safely ``fail``.
    """

    def __init__(self, prefix: str = "jepsen", timeout_s: float = 5.0,
                 node: str | None = None):
        self.prefix = prefix
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return EtcdClient(self.prefix, self.timeout_s, node)

    def _url(self, path: str, **params) -> str:
        q = f"?{urllib.parse.urlencode(params)}" if params else ""
        return (f"{node_url(self.node, CLIENT_PORT)}/v2/keys/"
                f"{urllib.parse.quote(path)}{q}")

    def _request(self, url: str, data: dict | None = None,
                 method: str = "GET") -> dict:
        body = urllib.parse.urlencode(data).encode() if data else None
        req = urllib.request.Request(url, data=body, method=method)
        if body:
            req.add_header("Content-Type", "application/x-www-form-urlencoded")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode())

    def _read_register(self, k):
        try:
            doc = self._request(self._url(f"{self.prefix}/{k}", quorum="true"))
            return int(doc["node"]["value"])
        except urllib.error.HTTPError as e:
            if e.code == 404:  # key not yet written
                return None
            raise

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "add":
                self._request(self._url(f"{self.prefix}-set/{v}"),
                              {"value": str(v)}, method="PUT")
                return {**op, "type": "ok"}
            if f == "read" and v is None:  # whole-set read
                try:
                    doc = self._request(self._url(f"{self.prefix}-set",
                                                  recursive="true",
                                                  quorum="true"))
                    nodes = (doc.get("node") or {}).get("nodes") or []
                    elems = sorted(int(n["key"].rsplit("/", 1)[-1])
                                   for n in nodes)
                except urllib.error.HTTPError as e:
                    if e.code != 404:
                        raise
                    elems = []
                return {**op, "type": "ok", "value": elems}
            if f == "read":
                k, _ = v
                return {**op, "type": "ok",
                        "value": [k, self._read_register(k)]}
            if f == "write":
                k, val = v
                self._request(self._url(f"{self.prefix}/{k}"),
                              {"value": str(val)}, method="PUT")
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                try:
                    self._request(self._url(f"{self.prefix}/{k}",
                                            prevValue=str(old)),
                                  {"value": str(new)}, method="PUT")
                    return {**op, "type": "ok"}
                except urllib.error.HTTPError as e:
                    # 412 = compare failed, 404 = key not yet written —
                    # both definite no-ops (the tutorial client maps
                    # key-not-found cas to :fail too)
                    if e.code in (412, 404):
                        return {**op, "type": "fail"}
                    raise
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            # 5xx is expected during faults (raft internal error / leader
            # election) — indeterminate for mutations, safe fail for reads.
            # Anything else HTTP-level (unhandled 4xx) is a real bug (wrong
            # API, misconfiguration) — surface it rather than logging noise.
            if e.code >= 500:
                kind = "fail" if f == "read" else "info"
                return {**op, "type": kind, "error": ["http", e.code]}
            raise
        except (TimeoutError, urllib.error.URLError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


SUPPORTED_WORKLOADS = ("register", "set")


def etcd_test(opts_dict: dict | None = None) -> dict:
    """Test-map constructor (the zookeeper.clj:105-137 shape)."""
    return build_suite_test(
        opts_dict, db_name="etcd", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": EtcdDB(o.get("version", DEFAULT_VERSION)),
                             "client": EtcdClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(etcd_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-etcd")


if __name__ == "__main__":
    import sys
    sys.exit(main())
