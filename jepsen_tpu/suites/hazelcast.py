"""Hazelcast test suite (reference: hazelcast/src/jepsen/hazelcast.clj
— a 5-node Hazelcast member cluster probed through queue, map,
atomic-long unique-id, CAS, semaphore, and four strengths of
CP-FencedLock clients; the queue client offers/polls and drains at the
end, checked with total-queue :266-317; the lock clients are checked
linearizable against owner/reentrancy/fence-aware mutex models
:516-650).

Two transports:

- **queue/map** ride Hazelcast's REST data endpoint
  (``/hazelcast/rest/queues/<q>``): enqueue = POST offer, dequeue =
  poll with a bounded timeout, drain = poll-until-empty — the REST-era
  equivalent of the reference's queue-client (hazelcast.clj:270-296).
- **CP workloads** (lock family, cp-cas, ids, semaphore) ride the
  from-scratch Open Binary Client Protocol client
  (:mod:`jepsen_tpu.suites._hazelcast`): authentication, Raft-group
  resolution, CP sessions with lazy heartbeats, AtomicLong /
  FencedLock / Semaphore invocations — the same capability surface as
  the reference's Java-client CP workloads (hazelcast.clj:146-264,
  345-411).

DB automation unpacks the Hazelcast distribution, writes a tcp-ip
member list plus REST-endpoint-groups + CP-subsystem config, and runs
bin/hz-start — the install!/configure!/start! cycle of
hazelcast.clj:57-116.
"""
from __future__ import annotations

import logging
import time
import socket
import urllib.error

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.fakes import MetaLogDB
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._hazelcast import INVALID_FENCE, HzClient, HzError
from jepsen_tpu.suites._http import NET_ERRORS, http_json, quote
from jepsen_tpu.workloads import cp_lock as cp_wl

logger = logging.getLogger("jepsen.hazelcast")

DEFAULT_VERSION = "5.3.7"
DIR = "/opt/hazelcast"
LOG_FILE = f"{DIR}/jepsen.log"
PIDFILE = f"{DIR}/hz.pid"
PORT = 5701
QUEUE = "jepsen.queue"
POLL_TIMEOUT_S = 1

CONFIG_YAML = """hazelcast:
  cluster-name: jepsen
  network:
    port:
      port: %(port)d
    rest-api:
      enabled: true
      endpoint-groups:
        DATA:
          enabled: true
    join:
      multicast:
        enabled: false
      tcp-ip:
        enabled: true
        member-list: [%(members)s]
  queue:
    %(queue)s:
      backup-count: 2
  cp-subsystem:
    cp-member-count: %(cp_members)d
    session-time-to-live-seconds: 30
    session-heartbeat-interval-seconds: 5
"""


def archive_url(version: str) -> str:
    return ("https://repository.hazelcast.com/download/hazelcast/"
            f"hazelcast-{version}.tar.gz")


class HazelcastDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing hazelcast %s", node, self.version)
        from jepsen_tpu import control
        cu.install_archive(archive_url(self.version), DIR)
        nodes = test.get("nodes") or []
        members = ", ".join(nodes)
        control.exec_("tee", f"{DIR}/config/hazelcast.yaml",
                      stdin=CONFIG_YAML % {"port": PORT, "members": members,
                                           "queue": QUEUE,
                                           "cp_members": max(3, len(nodes))})
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/logs")

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR,
             "env": {"HAZELCAST_CONFIG": f"{DIR}/config/hazelcast.yaml"}},
            f"{DIR}/bin/hz-start")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/bin/hz-start", PIDFILE)
        cu.grepkill("com.hazelcast.core.server.HazelcastMemberStarter")

    def pause(self, test, node):
        cu.grepkill("com.hazelcast.core.server.HazelcastMemberStarter",
                    sig="STOP")

    def resume(self, test, node):
        cu.grepkill("com.hazelcast.core.server.HazelcastMemberStarter",
                    sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class HazelcastClient(Client):
    """Queue ops over the REST data endpoint group."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return HazelcastClient(self.timeout_s, node)

    def _base_url(self) -> str:
        return (f"http://{self.node}:{PORT}/hazelcast/rest/queues/"
                f"{quote(QUEUE)}")

    def _offer(self, v) -> None:
        """POST with the value as the request body."""
        http_json(self._base_url(), method="POST",
                  raw_body=str(v).encode(),
                  headers={"Content-Type": "text/plain"},
                  timeout_s=self.timeout_s)

    def _poll(self):
        """DELETE /queues/<q>/<timeout-s>; the item (str) or None when
        empty (204 / empty body)."""
        raw = http_json(f"{self._base_url()}/{POLL_TIMEOUT_S}",
                        method="DELETE",
                        timeout_s=self.timeout_s + POLL_TIMEOUT_S)
        if raw is None or raw == "":
            return None
        return raw

    def _map_url(self, k) -> str:
        return (f"http://{self.node}:{PORT}/hazelcast/rest/maps/"
                f"jepsen/{quote(str(k))}")

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if f in ("read", "write") and isinstance(v, (list, tuple)):
            # map workload: IMap get/put over the REST map endpoint (the
            # REST surface has no CAS, so the r/w register subset runs)
            try:
                if f == "read":
                    k, _ = v
                    raw = http_json(self._map_url(k),
                                    timeout_s=self.timeout_s)
                    val = int(raw) if raw not in (None, "") else None
                    return {**op, "type": "ok", "value": [k, val]}
                k, val = v
                http_json(self._map_url(k), method="POST",
                          raw_body=str(int(val)).encode(),
                          headers={"Content-Type": "text/plain"},
                          timeout_s=self.timeout_s)
                return {**op, "type": "ok"}
            except urllib.error.HTTPError as e:
                # HTTPError subclasses URLError: catch it FIRST or HTTP
                # failures masquerade as network errors (the queue
                # branch's ordering)
                kind = "fail" if f == "read" else "info"
                return {**op, "type": kind, "error": ["http", e.code]}
            except NET_ERRORS as e:
                kind = "fail" if f == "read" else "info"
                return {**op, "type": kind, "error": ["net", str(e)]}
        drained: list = []
        try:
            if f == "enqueue":
                self._offer(v)
                return {**op, "type": "ok"}
            if f == "dequeue":
                raw = self._poll()
                if raw is None:
                    return {**op, "type": "fail"}
                return {**op, "type": "ok", "value": int(raw)}
            if f == "drain":
                while True:
                    raw = self._poll()
                    if raw is None:
                        return {**op, "type": "ok", "value": drained}
                    drained.append(int(raw))
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            if f == "drain":
                # elements already polled were consumed: keep them in the
                # indeterminate completion so total-queue doesn't count
                # them lost
                return {**op, "type": "info", "value": drained,
                        "error": ["http", e.code]}
            kind = "fail" if f == "dequeue" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            if f == "drain":
                return {**op, "type": "info", "value": drained,
                        "error": ["net", str(e)]}
            kind = "fail" if f == "dequeue" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


# -- CP-subsystem clients (wire protocol) -----------------------------------

LOCK_NAME = "jepsen.cpLock"
SEMAPHORE_NAME = "jepsen.cpSemaphore"
ATOMIC_NAME = "jepsen.atomic-long"
CAS_NAME = "jepsen.cas-long"

# workload name -> which object family the binary-protocol client
# drives (everything here rides HzCPClient; "map"-named modes use IMap
# over the same connection, CP modes add Raft-group/session plumbing)
CP_MODES = {
    "lock": "lock", "cp-lock": "lock", "reentrant-cp-lock": "lock",
    "fenced-lock": "lock", "reentrant-fenced-lock": "lock",
    "cp-semaphore": "semaphore",
    "atomic-long-ids": "ids", "cp-id-gen-long": "ids",
    "cp-cas-long": "cas",
    "atomic-ref-ids": "ref-ids", "cp-cas-reference": "cas-ref",
    "id-gen-ids": "flake-ids",
    "map-set": "map", "crdt-map": "crdt",
}

MAP_KEY = "hi"   # the reference map workload's single contended key
REF_NAME = "jepsen.atomic-ref"
FLAKE_NAME = "jepsen.id-gen"


class HzCPClient(Client):
    """CP-subsystem ops over the binary protocol (the counterpart of
    hazelcast.clj's fenced-lock-client :339-370, cp-semaphore-client
    :372-411, cp-atomic-long-id-client :174-188, cp-cas-long-client
    :190-209). Error mapping follows the reference: lock-owner
    violations fail, transport errors that may have applied complete
    info."""

    def __init__(self, mode: str = "lock", node: str | None = None,
                 conn: HzClient | None = None, timeout_s: float = 10.0):
        self.mode = mode
        self.node = node
        self.conn = conn
        self.timeout_s = timeout_s

    def open(self, test, node):
        conn = HzClient(node, PORT, timeout_s=self.timeout_s).connect()
        if self.mode == "semaphore":
            try:
                conn.semaphore_init(SEMAPHORE_NAME, cp_wl.NUM_PERMITS)
            except HzError:
                pass  # already initialised by a sibling
        if self.mode == "cas-ref":
            # ground a fresh (nil) ref at 0 so the CAS-register model's
            # initial state is exact. A LOSING CAS returns False (some
            # sibling grounded first) — that's fine; an HzError is a
            # real failure, and swallowing it would leave nil reads
            # that the model misreads as a linearizability violation,
            # so retry briefly and otherwise let open() fail loudly.
            for attempt in range(5):
                try:
                    conn.atomic_ref_compare_and_set(REF_NAME, None, 0)
                    break
                except HzError:
                    if attempt == 4:
                        raise
                    time.sleep(0.5)
        return HzCPClient(self.mode, node, conn, self.timeout_s)

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if self.conn.sock is None:   # dropped after a net error
                self.conn.connect()
            if self.mode == "lock":
                if f == "acquire":
                    fence = self.conn.lock_try_lock(LOCK_NAME)
                    if fence == INVALID_FENCE:
                        return {**op, "type": "fail"}
                    return {**op, "type": "ok", "value": fence}
                if f == "release":
                    self.conn.lock_unlock(LOCK_NAME)
                    return {**op, "type": "ok"}
            elif self.mode == "semaphore":
                if f == "acquire":
                    ok = self.conn.semaphore_acquire(SEMAPHORE_NAME)
                    return {**op, "type": "ok" if ok else "fail"}
                if f == "release":
                    self.conn.semaphore_release(SEMAPHORE_NAME)
                    return {**op, "type": "ok"}
            elif self.mode == "ids":
                if f == "generate":
                    v = self.conn.atomic_add_and_get(ATOMIC_NAME, 1)
                    return {**op, "type": "ok", "value": v}
            elif self.mode == "ref-ids":
                if f == "generate":
                    # optimistic increment over a CP AtomicReference
                    # (hazelcast.clj:232-249 atomic-ref-id-client)
                    v = self.conn.atomic_ref_get(REF_NAME)
                    v2 = (v or 0) + 1
                    if self.conn.atomic_ref_compare_and_set(REF_NAME,
                                                            v, v2):
                        return {**op, "type": "ok", "value": v2}
                    return {**op, "type": "fail", "error": "cas-failed"}
            elif self.mode == "flake-ids":
                if f == "generate":
                    base, _inc, _n = self.conn.flake_id_batch(FLAKE_NAME)
                    return {**op, "type": "ok", "value": base}
            elif self.mode == "cas-ref":
                v = op.get("value")
                if f == "read":
                    return {**op, "type": "ok",
                            "value": self.conn.atomic_ref_get(REF_NAME)}
                if f == "write":
                    self.conn.atomic_ref_set(REF_NAME, int(v))
                    return {**op, "type": "ok"}
                if f == "cas":
                    old, new = v
                    if self.conn.atomic_ref_compare_and_set(
                            REF_NAME, int(old), int(new)):
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail", "error": "cas-failed"}
            elif self.mode in ("map", "crdt"):
                from jepsen_tpu.suites import _hazelcast as wire
                name = "jepsen.crdt-map" if self.mode == "crdt" \
                    else "jepsen.map"
                key = wire.data_string(MAP_KEY)
                if f == "add":
                    # long-array CRDT-ish set under one key, grown by
                    # server-side CAS (hazelcast.clj:453-506: replace /
                    # putIfAbsent over sorted long arrays — hazelcast
                    # serialization can't merge HashSets)
                    cur = self.conn.map_get_raw(name, key)
                    if cur is None:
                        won = self.conn.map_put_if_absent(
                            name, key, wire.data_long_array([int(v)]))
                        if won is None:
                            return {**op, "type": "ok"}
                        return {**op, "type": "fail",
                                "error": "cas-failed"}
                    have = wire.decode_data(cur) or []
                    new = sorted(set(have) | {int(v)})
                    if self.conn.map_replace_if_same(
                            name, key, cur, wire.data_long_array(new)):
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail", "error": "cas-failed"}
                if f == "read":
                    got = self.conn.map_get(name, key)
                    return {**op, "type": "ok",
                            "value": sorted(got or [])}
            elif self.mode == "cas":
                v = op.get("value")
                if f == "read":
                    return {**op, "type": "ok",
                            "value": self.conn.atomic_get(CAS_NAME)}
                if f == "write":
                    self.conn.atomic_get_and_set(CAS_NAME, int(v))
                    return {**op, "type": "ok"}
                if f == "cas":
                    old, new = v
                    ok = self.conn.atomic_compare_and_set(
                        CAS_NAME, int(old), int(new))
                    if ok:
                        return {**op, "type": "ok"}
                    return {**op, "type": "fail", "error": "cas-failed"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except HzError as e:
            if "IllegalMonitorState" in e.class_name:
                return {**op, "type": "fail", "error": "not-lock-owner"}
            # reads can safely fail; any other errored op may still have
            # applied server-side (e.g. an indeterminate Raft commit), so
            # it must complete info or the lock models see phantom frees
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind,
                    "error": ["hz", e.class_name, e.message]}
        except (ConnectionError, socket.timeout, OSError) as e:
            # the stream may hold a half-read response: drop the
            # connection so the next invoke reconnects cleanly instead
            # of desynchronizing the frame decoder
            if self.conn is not None:
                self.conn.close()
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            try:
                for g in list(self.conn._groups.values()):
                    self.conn.close_session(g)
            except (HzError, ConnectionError, socket.timeout, OSError):
                pass  # best-effort: the server reaps expired sessions
            self.conn.close()


# -- fake-mode CP doubles ---------------------------------------------------

class CPFakeStore(MetaLogDB):
    """In-memory CP subsystem: a reentrant fenced lock, a counting
    semaphore, an atomic long, and an id counter — the cluster double
    the fake-mode lifecycle tests run the CP workloads against."""

    def __init__(self, max_holds: int = cp_wl.MAX_HOLDS,
                 permits: int = cp_wl.NUM_PERMITS):
        super().__init__()
        self.max_holds = max_holds
        self.permits = permits
        self._wipe()

    def _wipe(self):
        self.holder = None
        self.holds = 0
        self.fence = 0
        self.fence_counter = 0
        self.sem: dict = {}
        self.along = 0
        self.ids = 0
        self.ref = None
        self.map_set: set = set()

    def try_lock(self, p) -> int:
        """Fence if acquired (same fence on reentrant re-acquire), 0 if
        busy or at max holds."""
        with self.lock:
            if self.holder is None:
                self.fence_counter += 1
                self.holder, self.holds = p, 1
                self.fence = self.fence_counter
                return self.fence
            if self.holder == p and self.holds < self.max_holds:
                self.holds += 1
                return self.fence
            return 0

    def unlock(self, p) -> bool:
        with self.lock:
            if self.holder != p:
                return False
            self.holds -= 1
            if self.holds == 0:
                self.holder = None
            return True

    def sem_acquire(self, p) -> bool:
        with self.lock:
            if sum(self.sem.values()) < self.permits:
                self.sem[p] = self.sem.get(p, 0) + 1
                return True
            return False

    def sem_release(self, p) -> bool:
        with self.lock:
            if self.sem.get(p, 0) > 0:
                self.sem[p] -= 1
                return True
            return False

    def next_id(self) -> int:
        with self.lock:
            self.ids += 1
            return self.ids

    def along_get(self) -> int:
        with self.lock:
            return self.along

    def along_set(self, v: int) -> None:
        with self.lock:
            self.along = v

    def along_cas(self, old: int, new: int) -> bool:
        with self.lock:
            if self.along == old:
                self.along = new
                return True
            return False

    def ref_get(self):
        with self.lock:
            return self.ref

    def ref_set(self, v) -> None:
        with self.lock:
            self.ref = v

    def ref_cas(self, old, new) -> bool:
        with self.lock:
            if self.ref == old:
                self.ref = new
                return True
            return False

    def ref_cas_grounded(self, old: int, new: int) -> bool:
        """CAS with a fresh (None) ref reading as 0 — the cas-ref
        client grounds the reference at 0 on open."""
        with self.lock:
            if (self.ref if self.ref is not None else 0) == old:
                self.ref = new
                return True
            return False

    def map_add(self, v: int) -> None:
        with self.lock:
            self.map_set.add(int(v))

    def map_read(self) -> list:
        with self.lock:
            return sorted(self.map_set)


class CPFakeClient(Client):
    """Fake-mode twin of HzCPClient over a CPFakeStore."""

    def __init__(self, store: CPFakeStore, mode: str,
                 node: str | None = None):
        self.store = store
        self.mode = mode
        self.node = node

    def open(self, test, node):
        self.store._note("client-open", node)
        return CPFakeClient(self.store, self.mode, node)

    def invoke(self, test, op):
        f, p = op.get("f"), op.get("process")
        if self.mode == "lock":
            if f == "acquire":
                fence = self.store.try_lock(p)
                if fence:
                    return {**op, "type": "ok", "value": fence}
                return {**op, "type": "fail"}
            if f == "release":
                if self.store.unlock(p):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "not-lock-owner"}
        elif self.mode == "semaphore":
            if f == "acquire":
                return {**op,
                        "type": "ok" if self.store.sem_acquire(p)
                        else "fail"}
            if f == "release":
                if self.store.sem_release(p):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "not-permit-owner"}
        elif self.mode == "ids":
            if f == "generate":
                return {**op, "type": "ok", "value": self.store.next_id()}
        elif self.mode in ("ref-ids", "flake-ids"):
            if f == "generate":
                if self.mode == "flake-ids":
                    return {**op, "type": "ok",
                            "value": self.store.next_id()}
                v = self.store.ref_get()
                v2 = (v or 0) + 1
                if self.store.ref_cas(v, v2):
                    return {**op, "type": "ok", "value": v2}
                return {**op, "type": "fail", "error": "cas-failed"}
        elif self.mode == "cas-ref":
            v = op.get("value")
            if f == "read":
                got = self.store.ref_get()
                return {**op, "type": "ok",
                        "value": got if got is not None else 0}
            if f == "write":
                self.store.ref_set(int(v))
                return {**op, "type": "ok"}
            if f == "cas":
                old, new = v
                if self.store.ref_cas_grounded(int(old), int(new)):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-failed"}
        elif self.mode in ("map", "crdt"):
            if f == "add":
                self.store.map_add(int(v))
                return {**op, "type": "ok"}
            if f == "read":
                return {**op, "type": "ok", "value": self.store.map_read()}
        elif self.mode == "cas":
            v = op.get("value")
            if f == "read":
                return {**op, "type": "ok", "value": self.store.along_get()}
            if f == "write":
                self.store.along_set(int(v))
                return {**op, "type": "ok"}
            if f == "cas":
                old, new = v
                if self.store.along_cas(int(old), int(new)):
                    return {**op, "type": "ok"}
                return {**op, "type": "fail", "error": "cas-failed"}
        return {**op, "type": "fail", "error": ["unknown-f", f]}


SUPPORTED_WORKLOADS = ("queue", "map", "map-set", "crdt-map", "lock",
                       "cp-lock", "reentrant-cp-lock", "fenced-lock",
                       "reentrant-fenced-lock", "cp-semaphore",
                       "atomic-long-ids", "cp-id-gen-long",
                       "atomic-ref-ids", "id-gen-ids", "cp-cas-long",
                       "cp-cas-reference")


def _hazelcast_workload(name: str, base: dict) -> dict:
    """map = the r/w register subset over REST (kept for transport
    parity); map-set / crdt-map = the reference's long-array CAS set
    over the binary protocol (set checker); the CP workloads ride the
    kits in workloads/cp_lock.py against the binary-protocol client."""
    acc = base["accelerator"]
    if name == "map":
        from jepsen_tpu.workloads import register as register_wl
        return register_wl.workload(base, accelerator=acc, ops=("r", "w"))
    if name in ("map-set", "crdt-map"):
        from jepsen_tpu.workloads import set_workload
        wl = set_workload.workload(base, accelerator=acc)
        wl["stats_ungated_fs"] = ("add",)   # CAS-raced adds fail
        return wl
    if name in ("lock", "cp-lock", "reentrant-cp-lock", "fenced-lock",
                "reentrant-fenced-lock"):
        return cp_wl.lock_workload(base, accelerator=acc, flavor=name)
    if name == "cp-semaphore":
        return cp_wl.semaphore_workload(base, accelerator=acc)
    if name in ("atomic-long-ids", "cp-id-gen-long", "atomic-ref-ids",
                "id-gen-ids"):
        wl = cp_wl.ids_workload(base, accelerator=acc)
        if name == "atomic-ref-ids":
            wl["stats_ungated_fs"] = ("generate",)   # optimistic CAS
        return wl
    if name in ("cp-cas-long", "cp-cas-reference"):
        return cp_wl.cas_long_workload(base, accelerator=acc)
    from jepsen_tpu.suites import workload_registry

    return workload_registry()[name](base, accelerator=acc)


def hazelcast_test(opts_dict: dict | None = None) -> dict:
    o = dict(opts_dict or {})
    workload = o.get("workload") or SUPPORTED_WORKLOADS[0]
    mode = CP_MODES.get(workload)
    store = CPFakeStore()
    return build_suite_test(
        o, db_name="hazelcast",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_workload=_hazelcast_workload,
        fake_db=(lambda: store) if mode else None,
        fake_client=(lambda: CPFakeClient(store, mode)) if mode else None,
        make_real=lambda opts: {
            "db": HazelcastDB(opts.get("version", DEFAULT_VERSION)),
            "client": (HzCPClient(mode) if mode else HazelcastClient()),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(hazelcast_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-hazelcast")


if __name__ == "__main__":
    import sys
    sys.exit(main())
