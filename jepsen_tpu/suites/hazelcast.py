"""Hazelcast test suite (reference: hazelcast/src/jepsen/hazelcast.clj
— a 5-node Hazelcast member cluster probed through queue, atomic-long
unique-id, CAS, and lock clients; the queue client offers/polls and
drains at the end, checked with total-queue :266-317).

This suite carries the queue workload over Hazelcast's REST map/queue
API (``/hazelcast/rest/queues/<q>``): enqueue = POST offer, dequeue =
poll with a bounded timeout, drain = poll-until-empty — the REST-era
equivalent of the reference's queue-client (hazelcast.clj:270-296).
The CP-subsystem clients (atomic long, cas register, fenced lock) are
only reachable through the Java client protocol and are out of REST
scope; run CAS workloads against the suites with server-side CAS
(etcd, zookeeper, ignite, consul).

DB automation unpacks the Hazelcast distribution, writes a tcp-ip
member list plus REST-endpoint-groups config, and runs bin/hz-start —
the install!/configure!/start! cycle of hazelcast.clj:57-116.
"""
from __future__ import annotations

import logging
import urllib.error

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS, http_json, quote

logger = logging.getLogger("jepsen.hazelcast")

DEFAULT_VERSION = "5.3.7"
DIR = "/opt/hazelcast"
LOG_FILE = f"{DIR}/jepsen.log"
PIDFILE = f"{DIR}/hz.pid"
PORT = 5701
QUEUE = "jepsen.queue"
POLL_TIMEOUT_S = 1

CONFIG_YAML = """hazelcast:
  cluster-name: jepsen
  network:
    port:
      port: %(port)d
    rest-api:
      enabled: true
      endpoint-groups:
        DATA:
          enabled: true
    join:
      multicast:
        enabled: false
      tcp-ip:
        enabled: true
        member-list: [%(members)s]
  queue:
    %(queue)s:
      backup-count: 2
"""


def archive_url(version: str) -> str:
    return ("https://repository.hazelcast.com/download/hazelcast/"
            f"hazelcast-{version}.tar.gz")


class HazelcastDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing hazelcast %s", node, self.version)
        from jepsen_tpu import control
        cu.install_archive(archive_url(self.version), DIR)
        members = ", ".join(test.get("nodes") or [])
        control.exec_("tee", f"{DIR}/config/hazelcast.yaml",
                      stdin=CONFIG_YAML % {"port": PORT, "members": members,
                                           "queue": QUEUE})
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(f"{DIR}/logs")

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR,
             "env": {"HAZELCAST_CONFIG": f"{DIR}/config/hazelcast.yaml"}},
            f"{DIR}/bin/hz-start")

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/bin/hz-start", PIDFILE)
        cu.grepkill("com.hazelcast.core.server.HazelcastMemberStarter")

    def pause(self, test, node):
        cu.grepkill("com.hazelcast.core.server.HazelcastMemberStarter",
                    sig="STOP")

    def resume(self, test, node):
        cu.grepkill("com.hazelcast.core.server.HazelcastMemberStarter",
                    sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class HazelcastClient(Client):
    """Queue ops over the REST data endpoint group."""

    def __init__(self, timeout_s: float = 5.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return HazelcastClient(self.timeout_s, node)

    def _base_url(self) -> str:
        return (f"http://{self.node}:{PORT}/hazelcast/rest/queues/"
                f"{quote(QUEUE)}")

    def _offer(self, v) -> None:
        """POST with the value as the request body."""
        http_json(self._base_url(), method="POST",
                  raw_body=str(v).encode(),
                  headers={"Content-Type": "text/plain"},
                  timeout_s=self.timeout_s)

    def _poll(self):
        """DELETE /queues/<q>/<timeout-s>; the item (str) or None when
        empty (204 / empty body)."""
        raw = http_json(f"{self._base_url()}/{POLL_TIMEOUT_S}",
                        method="DELETE",
                        timeout_s=self.timeout_s + POLL_TIMEOUT_S)
        if raw is None or raw == "":
            return None
        return raw

    def _map_url(self, k) -> str:
        return (f"http://{self.node}:{PORT}/hazelcast/rest/maps/"
                f"jepsen/{quote(str(k))}")

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if f in ("read", "write") and isinstance(v, (list, tuple)):
            # map workload: IMap get/put over the REST map endpoint (the
            # REST surface has no CAS, so the r/w register subset runs)
            try:
                if f == "read":
                    k, _ = v
                    raw = http_json(self._map_url(k),
                                    timeout_s=self.timeout_s)
                    val = int(raw) if raw not in (None, "") else None
                    return {**op, "type": "ok", "value": [k, val]}
                k, val = v
                http_json(self._map_url(k), method="POST",
                          raw_body=str(int(val)).encode(),
                          headers={"Content-Type": "text/plain"},
                          timeout_s=self.timeout_s)
                return {**op, "type": "ok"}
            except urllib.error.HTTPError as e:
                # HTTPError subclasses URLError: catch it FIRST or HTTP
                # failures masquerade as network errors (the queue
                # branch's ordering)
                kind = "fail" if f == "read" else "info"
                return {**op, "type": kind, "error": ["http", e.code]}
            except NET_ERRORS as e:
                kind = "fail" if f == "read" else "info"
                return {**op, "type": kind, "error": ["net", str(e)]}
        drained: list = []
        try:
            if f == "enqueue":
                self._offer(v)
                return {**op, "type": "ok"}
            if f == "dequeue":
                raw = self._poll()
                if raw is None:
                    return {**op, "type": "fail"}
                return {**op, "type": "ok", "value": int(raw)}
            if f == "drain":
                while True:
                    raw = self._poll()
                    if raw is None:
                        return {**op, "type": "ok", "value": drained}
                    drained.append(int(raw))
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            if f == "drain":
                # elements already polled were consumed: keep them in the
                # indeterminate completion so total-queue doesn't count
                # them lost
                return {**op, "type": "info", "value": drained,
                        "error": ["http", e.code]}
            kind = "fail" if f == "dequeue" else "info"
            return {**op, "type": kind, "error": ["http", e.code]}
        except NET_ERRORS as e:
            if f == "drain":
                return {**op, "type": "info", "value": drained,
                        "error": ["net", str(e)]}
            kind = "fail" if f == "dequeue" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


SUPPORTED_WORKLOADS = ("queue", "map")


def _hazelcast_workload(name: str, base: dict) -> dict:
    """map = the r/w register subset (the REST map API exposes get/put
    but no CAS; hazelcast.clj's richer map workloads ride the native
    client protocol — see PARITY's protocol-bounded scope note)."""
    if name == "map":
        from jepsen_tpu.workloads import register as register_wl
        return register_wl.workload(base, accelerator=base["accelerator"],
                                    ops=("r", "w"))
    from jepsen_tpu.suites import workload_registry

    return workload_registry()[name](base, accelerator=base["accelerator"])


def hazelcast_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="hazelcast",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_workload=_hazelcast_workload,
        make_real=lambda o: {
            "db": HazelcastDB(o.get("version", DEFAULT_VERSION)),
            "client": HazelcastClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(hazelcast_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-hazelcast")


if __name__ == "__main__":
    import sys
    sys.exit(main())
