"""MariaDB Galera Cluster test suite (reference:
galera/src/jepsen/galera.clj + galera/dirty_reads.clj — a multi-primary
synchronous-replication MySQL whose classic anomalies are dirty reads of
aborted transactions and broken snapshot sums).

Workloads: ``set`` (auto-increment insert table, galera.clj:214-258),
``bank`` (serializable transfers whose reads must preserve the total,
galera.clj:260-383), and ``dirty-reads`` (writers racing to set every
row while readers scan, dirty_reads.clj). All ride the shared
MySQL-wire suite client (``_mysql_client.py``), connecting each client
to its own node — galera is multi-primary (galera.clj:86-93).

DB automation mirrors galera.clj:34-131: install the mariadb server
package, write a wsrep config with ``gcomm://`` cluster address,
bootstrap the first node as a new cluster, start the rest after a
barrier, then create the jepsen database and user.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._mysql_client import (MySQLSuiteClient,
                                             create_db_and_user)

logger = logging.getLogger("jepsen.galera")

PORT = 3306
DB_NAME = "jepsen"
DB_USER = "jepsen"
DB_PASS = "jepsen"
DATA_DIR = "/var/lib/mysql"
CONF_FILE = "/etc/mysql/conf.d/jepsen.cnf"
LOG_FILES = ["/var/log/syslog", "/var/log/mysql.log", "/var/log/mysql.err"]


def cluster_address(test: dict) -> str:
    """``gcomm://n1,n2,...`` (galera.clj:59-62)."""
    return "gcomm://" + ",".join(test.get("nodes") or [])


GALERA_PROVIDER = "/usr/lib/galera/libgalera_smm.so"


def wsrep_config(test: dict, provider: str = GALERA_PROVIDER) -> str:
    """The jepsen.cnf wsrep settings (galera.clj resources/jepsen.cnf).
    ``provider`` varies by distribution: mariadb's galera-4 package owns
    /usr/lib/galera/, percona-xtradb-cluster bundles galera-3 under
    /usr/lib/galera3/."""
    return "\n".join([
        "[mysqld]",
        "bind-address = 0.0.0.0",
        "binlog_format = ROW",
        "default_storage_engine = InnoDB",
        "innodb_autoinc_lock_mode = 2",
        "wsrep_on = ON",
        f"wsrep_provider = {provider}",
        f"wsrep_cluster_address = {cluster_address(test)}",
        "wsrep_cluster_name = jepsen",
        "wsrep_sst_method = rsync",
        "",
    ])


class GaleraDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Galera lifecycle (galera.clj:102-131): package install, wsrep
    config, --wsrep-new-cluster bootstrap on node 1, barrier, join."""

    def __init__(self, package: str = "mariadb-server"):
        self.package = package

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        logger.info("%s: installing %s", node, self.package)
        os_setup.install([self.package, "galera-4", "rsync"])
        control.exec_(control.lit(
            "service mysql stop >/dev/null 2>&1 || true"))
        cu.mkdir("/etc/mysql/conf.d")
        cu.write_file(wsrep_config(test), CONF_FILE)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            # first node bootstraps a new cluster (galera.clj:110-111)
            control.exec_(control.lit(
                "galera_new_cluster || service mysql start "
                "--wsrep-new-cluster"))
        core.synchronize(test, timeout_s=300.0)
        if node != primary:
            control.exec_("service", "mysql", "start")
        core.synchronize(test, timeout_s=300.0)
        cu.await_tcp_port(PORT, host=node)
        create_db_and_user(DB_NAME, DB_USER, DB_PASS)

    def teardown(self, test, node):
        self.kill(test, node)
        control.exec_(control.lit(
            f"mysql -u root -e 'DROP DATABASE IF EXISTS {DB_NAME}' "
            ">/dev/null 2>&1 || true"))

    def start(self, test, node):
        control.exec_("service", "mysql", "start")

    def kill(self, test, node):
        control.exec_(control.lit(
            "service mysql stop >/dev/null 2>&1 || true"))
        cu.grepkill("mysqld")

    def pause(self, test, node):
        cu.grepkill("mysqld", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("mysqld", sig="CONT")

    def log_files(self, test, node):
        return LOG_FILES


SUPPORTED_WORKLOADS = ("set", "bank", "dirty-reads")


def galera_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="galera", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {
            "db": GaleraDB(),
            "client": MySQLSuiteClient(
                port=PORT, database=DB_NAME, user=DB_USER, password=DB_PASS,
                isolation=o.get("isolation", "serializable")),
            "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(galera_test, extra_keys=("isolation",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--isolation", default="serializable",
                        choices=["read-committed", "repeatable-read",
                                 "serializable"])),
    name="jepsen-galera")


if __name__ == "__main__":
    import sys
    sys.exit(main())
