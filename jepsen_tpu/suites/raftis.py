"""Raftis test suite (reference: raftis/src/jepsen/raftis.clj — PikaLabs
floyd's raft-replicated redis-compatible server).

The reference workload is a linearizable r/w register over the redis
protocol under random-halves partitions (raftis.clj:111-134); its
error discipline is the interesting part: reads that fail are definite
``fail``, writes are indeterminate ``info`` *unless* the server said
"no leader" or the socket closed before the request could have been
accepted (raftis.clj:37-58). We keep exactly that mapping.

DB automation mirrors raftis.clj:79-109: install a release tarball,
start the daemon with the full ``host:8901`` cluster string, serve
clients on 6379.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._resp import RespConnection, RespError

logger = logging.getLogger("jepsen.raftis")

DEFAULT_VERSION = "v2.0.4"
DIR = "/opt/raftis"
LOG_FILE = f"{DIR}/data/LOG"
DAEMON_LOG = f"{DIR}/raftis.log"
PIDFILE = f"{DIR}/raftis.pid"
BINARY = "raftis"
RAFT_PORT = 8901
CLIENT_PORT = 6379


def archive_url(version: str) -> str:
    return (f"https://github.com/PikaLabs/floyd/releases/download/"
            f"{version}/raftis-{version}.tar.gz")


def initial_cluster(test: dict) -> str:
    """``n1:8901,n2:8901,...`` (raftis.clj:70-77)."""
    return ",".join(f"{n}:{RAFT_PORT}" for n in (test.get("nodes") or []))


class RaftisDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Raftis lifecycle (raftis.clj:79-109): archive install + daemon with
    cluster-string/node/raft-port/data-dir/client-port argv."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing raftis %s", node, self.version)
        if not cu.file_exists(f"{DIR}/{BINARY}"):
            cu.install_archive(archive_url(self.version), DIR)
        self.start(test, node)
        cu.await_tcp_port(CLIENT_PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DIR)

    def start(self, test, node):
        return cu.start_daemon(
            {"logfile": DAEMON_LOG, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/{BINARY}", initial_cluster(test), node, str(RAFT_PORT),
            "data", str(CLIENT_PORT))

    def kill(self, test, node):
        cu.stop_daemon(BINARY, PIDFILE)
        cu.grepkill(BINARY)

    def pause(self, test, node):
        cu.grepkill(BINARY, sig="STOP")

    def resume(self, test, node):
        cu.grepkill(BINARY, sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE, DAEMON_LOG]


class RaftisClient(Client):
    """r/w/cas registers against the local node — raftis is multi-primary
    through raft, so every node accepts commands (raftis.clj:28-62).

    CAS is a server-side Lua EVAL like the redis suite's; floyd's redis
    front end accepts EVAL, and a rejection is a definite ``fail``.
    """

    def __init__(self, prefix: str = "jepsen", timeout_s: float = 5.0,
                 node: str | None = None):
        self.prefix = prefix
        self.timeout_s = timeout_s
        self.node = node
        self.conn: RespConnection | None = None

    def open(self, test, node):
        c = RaftisClient(self.prefix, self.timeout_s, node)
        c.conn = RespConnection(node, CLIENT_PORT, timeout_s=self.timeout_s)
        return c

    def invoke(self, test, op):
        from jepsen_tpu.suites.redis import CAS_LUA
        f, v = op.get("f"), op.get("value")
        try:
            if f == "read":
                k, _ = v
                raw = self.conn.command("GET", f"{self.prefix}:{k}")
                return {**op, "type": "ok",
                        "value": [k, int(raw) if raw is not None else None]}
            if f == "write":
                k, val = v
                self.conn.command("SET", f"{self.prefix}:{k}", val)
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                applied = self.conn.command(
                    "EVAL", CAS_LUA, 1, f"{self.prefix}:{k}", old, new)
                return {**op, "type": "ok" if applied == 1 else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except RespError as e:
            # "no leader" means the write was definitely not accepted
            # (raftis.clj:46-49); any server error on a read is a fail
            msg = str(e)
            definite = f == "read" or "no leader" in msg
            return {**op, "type": "fail" if definite else "info",
                    "error": ["resp", msg]}
        except (TimeoutError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        if self.conn is not None:
            self.conn.close()


SUPPORTED_WORKLOADS = ("register",)


def raftis_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="raftis", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": RaftisDB(o.get("version",
                                                  DEFAULT_VERSION)),
                             "client": RaftisClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(raftis_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-raftis")


if __name__ == "__main__":
    import sys
    sys.exit(main())
