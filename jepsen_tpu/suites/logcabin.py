"""LogCabin test suite (reference: logcabin/src/jepsen/logcabin.clj —
Diego Ongaro's original Raft implementation, tested as a linearizable
CAS register through its on-node ``TreeOps`` client binary).

Unlike the wire-protocol suites, the client here is *exec-based*: ops
run the TreeOps example binary on the db node over the control layer
(logcabin.clj:163-208), exactly as the reference does — read is
``TreeOps read /jepsen``, write pipes the value into ``TreeOps write``,
and CAS uses TreeOps's ``-p path:expected`` predicate flag, whose
distinctive "has value ... not ... as required" error marks a definite
CAS failure (logcabin.clj:152-155,189-208).

DB automation per logcabin.clj:24-148: scons-build from source, write
per-node serverId/listenAddresses config, ``--bootstrap`` the first
node's log, start daemons, then ``Reconfigure set`` the full membership
from the primary.
"""
from __future__ import annotations

import logging
import re

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)

logger = logging.getLogger("jepsen.logcabin")

PORT = 5254
CONFIG = "/root/logcabin.conf"
LOG_FILE = "/root/logcabin.log"
PID_FILE = "/root/logcabin.pid"
STORE_DIR = "/root/storage"
LOGCABIN_BIN = "/root/LogCabin"
RECONFIGURE_BIN = "/root/Reconfigure"
TREEOPS_BIN = "/root/TreeOps"
OP_TIMEOUT = 3
PATH = "/jepsen"

# TreeOps's CAS-mismatch and timeout errors (logcabin.clj:152-158)
CAS_MSG = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Path '.*' has value "
    r"'.*', not '.*' as required")
TIMEOUT_MSG = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Client-specified "
    r"timeout elapsed")


def server_id(node: str) -> str:
    """n3 → 3 (logcabin.clj:48-50)."""
    return node.replace("n", "")


def server_addrs(test: dict) -> str:
    return ",".join(f"{n}:{PORT}" for n in (test.get("nodes") or []))


class LogCabinDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Build, bootstrap node 1, start, reconfigure full membership
    (logcabin.clj:24-148)."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        os_setup.install(["git", "protobuf-compiler", "libprotobuf-dev",
                          "libcrypto++-dev", "g++", "scons"])
        if not cu.file_exists(TREEOPS_BIN):
            logger.info("%s: building logcabin", node)
            with control.cd("/"):
                if not cu.file_exists("/logcabin"):
                    control.exec_("git", "clone", "--depth", "1",
                                  "https://github.com/logcabin/logcabin.git")
            with control.cd("/logcabin"):
                control.exec_("git", "submodule", "update", "--init")
                control.exec_("scons")
            for f in ("LogCabin", "Examples/Reconfigure", "Examples/TreeOps"):
                control.exec_("cp", "-f", f"/logcabin/build/{f}", "/root")
        cu.write_file(f"serverId = {server_id(node)}\n"
                      f"listenAddresses = {node}:{PORT}\n"
                      f"storagePath = {STORE_DIR}\n", CONFIG)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            # bootstrap writes an initial single-server log
            control.exec_(LOGCABIN_BIN, "-c", CONFIG, "-l", LOG_FILE,
                          "--bootstrap")
        self.start(test, node)
        cu.await_tcp_port(PORT, host=node, timeout_s=600.0)
        core.synchronize(test, timeout_s=900.0)  # source build variance
        if node == primary:
            self.reconfigure(test, node)

    def reconfigure(self, test, node):
        """Grow the cluster to full membership (logcabin.clj:102-112)."""
        control.exec_(RECONFIGURE_BIN, "-c", server_addrs(test), "set",
                      *[f"{n}:{PORT}" for n in (test.get("nodes") or [])])

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(STORE_DIR)
        cu.rm_rf(LOG_FILE)

    def start(self, test, node):
        with control.cd("/root"):
            control.exec_(LOGCABIN_BIN, "-c", CONFIG, "-d", "-l", LOG_FILE,
                          "-p", PID_FILE)

    def kill(self, test, node):
        cu.grepkill("LogCabin")
        cu.rm_rf(PID_FILE)

    def pause(self, test, node):
        cu.grepkill("LogCabin", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("LogCabin", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class LogCabinClient(Client):
    """CAS register via the on-node TreeOps binary
    (logcabin.clj:163-246). Register values are stored as plain ints at
    one tree path per key: /jepsen-<k>."""

    def __init__(self, node: str | None = None):
        self.node = node
        self.test: dict | None = None

    def open(self, test, node):
        c = LogCabinClient(node)
        c.test = test
        return c

    def _exec(self, *args, stdin: str | None = None):
        return control.on(
            self.node, self.test,
            lambda: control.exec_star(
                TREEOPS_BIN, "-c", server_addrs(self.test), "-q",
                "-t", str(OP_TIMEOUT), *args, stdin=stdin))

    def _path(self, k) -> str:
        return f"{PATH}-{k}"

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "read":
                k, _ = v
                r = self._exec("read", self._path(k))
                if r.exit_status != 0:
                    return self._error(op, r)
                raw = (r.out or "").strip()
                return {**op, "type": "ok",
                        "value": [k, int(raw) if raw else None]}
            if f == "write":
                k, val = v
                r = self._exec("write", self._path(k), stdin=str(val))
                if r.exit_status != 0:
                    return self._error(op, r)
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                r = self._exec("-p", f"{self._path(k)}:{old}",
                               "write", self._path(k), stdin=str(new))
                if r.exit_status != 0:
                    msg = (r.err or r.out or "").strip()
                    if CAS_MSG.match(msg):
                        return {**op, "type": "fail"}  # precondition miss
                    return self._error(op, r)
                return {**op, "type": "ok"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except Exception as e:  # control-layer/SSH failure
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["exec", str(e)]}

    def _error(self, op, r):
        """TreeOps nonzero exit → typed completion. A read that never
        found the path is an empty register; timeouts are definite
        fails per the reference (logcabin.clj:238-241)."""
        msg = (r.err or r.out or "").strip()
        if op.get("f") == "read" and "does not exist" in msg:
            k, _ = op.get("value")
            return {**op, "type": "ok", "value": [k, None]}
        # deviation from logcabin.clj:238-241 (which fails ALL timed-out
        # ops): a timed-out write/cas may still have applied, so claiming
        # a definite fail could manufacture linearizability violations —
        # only reads are safe to fail on timeout
        kind = "fail" if op.get("f") == "read" else "info"
        if TIMEOUT_MSG.match(msg):
            return {**op, "type": kind, "error": ["timed-out"]}
        return {**op, "type": kind, "error": ["treeops", msg[:200]]}


SUPPORTED_WORKLOADS = ("register",)


def logcabin_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="logcabin",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": LogCabinDB(),
                             "client": LogCabinClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(logcabin_test),
    standard_opt_fn(SUPPORTED_WORKLOADS),
    name="jepsen-logcabin")


if __name__ == "__main__":
    import sys
    sys.exit(main())
