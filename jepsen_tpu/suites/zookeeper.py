"""ZooKeeper test suite (reference: zookeeper/src/jepsen/zookeeper.clj).

DB automation installs the distro zookeeper package, writes ``zoo.cfg``
with the full server ensemble plus a per-node ``myid``, and restarts the
service (zookeeper.clj:43-61). The client does single-znode r/w/cas via
`kazoo` when available (the reference uses an avout distributed atom —
same znode-version-CAS semantics); without kazoo installed the suite
still composes and runs in ``--fake`` mode over the in-memory doubles.
"""
from __future__ import annotations

import logging

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)

logger = logging.getLogger("jepsen.zookeeper")

CONF = "/etc/zookeeper/conf/zoo.cfg"
MYID = "/etc/zookeeper/conf/myid"
LOG = "/var/log/zookeeper/zookeeper.log"
DATA_DIR = "/var/lib/zookeeper"
CLIENT_PORT = 2181


def zoo_cfg(test: dict) -> str:
    """The ensemble config (zookeeper.clj:33-41 zoo-cfg)."""
    lines = [
        "tickTime=2000",
        "initLimit=10",
        "syncLimit=5",
        f"dataDir={DATA_DIR}",
        f"clientPort={CLIENT_PORT}",
    ]
    for i, node in enumerate(test.get("nodes") or [], start=1):
        lines.append(f"server.{i}={node}:2888:3888")
    return "\n".join(lines) + "\n"


def node_id(test: dict, node: str) -> int:
    """1-based id of a node in the ensemble (zookeeper.clj:28-31)."""
    return (test.get("nodes") or []).index(node) + 1


class ZookeeperDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """Distro-package zookeeper lifecycle (zookeeper.clj:43-61)."""

    def setup(self, test, node):
        logger.info("%s: installing zookeeper", node)
        from jepsen_tpu import os_setup
        os_setup.install(["zookeeper", "zookeeper-bin", "zookeeperd"])
        cu.write_file(str(node_id(test, node)), MYID)
        cu.write_file(zoo_cfg(test), CONF)
        control.exec_("service", "zookeeper", "restart")
        cu.await_tcp_port(CLIENT_PORT, host=node)

    def teardown(self, test, node):
        # cycle() tears down before the first setup (db.clj:121-158), so
        # tolerate a node where the service was never installed
        control.exec_(control.lit(
            "service zookeeper stop >/dev/null 2>&1 || true"))
        cu.rm_rf(f"{DATA_DIR}/version-2")
        cu.rm_rf(LOG)

    # db_mod.Process
    def start(self, test, node):
        control.exec_("service", "zookeeper", "start")

    def kill(self, test, node):
        cu.grepkill("zookeeper")

    # db_mod.Pause
    def pause(self, test, node):
        cu.grepkill("zookeeper", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("zookeeper", sig="CONT")

    # db_mod.LogFiles
    def log_files(self, test, node):
        return [LOG]


class ZookeeperClient(Client):
    """Per-key znode r/w/cas via kazoo, using znode versions for CAS
    (the semantics the reference gets from an avout atom). Register ops
    arrive independent-lifted with ``[k, v]`` tuple values
    (independent.clj:21-29); each key is a child znode. Set adds create
    child znodes under a set parent; whole-set reads list children."""

    def __init__(self, path: str = "/jepsen", timeout_s: float = 5.0,
                 node: str | None = None):
        self.path = path
        self.timeout_s = timeout_s
        self.node = node
        self.zk = None

    def open(self, test, node):
        try:
            from kazoo.client import KazooClient
        except ImportError as e:
            raise RuntimeError(
                "kazoo is not installed; run this suite with --fake or "
                "install kazoo for a real cluster") from e
        c = ZookeeperClient(self.path, self.timeout_s, node)
        c.zk = KazooClient(hosts=f"{node}:{CLIENT_PORT}",
                           timeout=self.timeout_s)
        c.zk.start(timeout=self.timeout_s)
        return c

    def setup(self, test):
        self.zk.ensure_path(self.path)
        self.zk.ensure_path(f"{self.path}-set")

    def _read(self, k):
        from kazoo.exceptions import NoNodeError
        try:
            data, stat = self.zk.get(f"{self.path}/{k}")
            return (int(data) if data else None), stat.version
        except NoNodeError:
            return None, None

    def invoke(self, test, op):
        from kazoo.exceptions import (BadVersionError, KazooException,
                                      NodeExistsError)
        f, v = op.get("f"), op.get("value")
        try:
            if f == "add":
                try:
                    self.zk.create(f"{self.path}-set/{v}", b"1",
                                   makepath=True)
                except NodeExistsError:
                    pass
                return {**op, "type": "ok"}
            if f == "read" and v is None:  # whole-set read
                elems = sorted(int(c) for c in
                               self.zk.get_children(f"{self.path}-set"))
                return {**op, "type": "ok", "value": elems}
            if f == "read":
                k, _ = v
                value, _version = self._read(k)
                return {**op, "type": "ok", "value": [k, value]}
            if f == "write":
                k, val = v
                znode = f"{self.path}/{k}"
                if self.zk.exists(znode) is None:
                    try:
                        self.zk.create(znode, str(val).encode(), makepath=True)
                        return {**op, "type": "ok"}
                    except NodeExistsError:
                        pass
                self.zk.set(znode, str(val).encode())
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                current, version = self._read(k)
                if version is None or current != old:
                    return {**op, "type": "fail"}
                try:
                    self.zk.set(f"{self.path}/{k}", str(new).encode(),
                                version=version)
                    return {**op, "type": "ok"}
                except BadVersionError:
                    return {**op, "type": "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except KazooException as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["zk", type(e).__name__]}

    def close(self, test):
        if self.zk is not None:
            try:
                self.zk.stop()
                self.zk.close()
            except Exception:  # noqa: BLE001
                pass


SUPPORTED_WORKLOADS = ("register", "set")


def zookeeper_test(opts_dict: dict | None = None) -> dict:
    """Test-map constructor (zookeeper.clj:105-137 zk-test)."""
    return build_suite_test(
        opts_dict, db_name="zookeeper",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": ZookeeperDB(),
                             "client": ZookeeperClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(zookeeper_test),
    standard_opt_fn(SUPPORTED_WORKLOADS),
    name="jepsen-zookeeper")


if __name__ == "__main__":
    import sys
    sys.exit(main())
