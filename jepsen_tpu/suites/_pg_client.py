"""Shared SQL client for the Postgres-protocol suite family — postgres,
cockroachdb, stolon, and yugabyte YSQL (reference: the jdbc client
layers in cockroachdb/src/jepsen/cockroach/client.clj,
stolon/src/jepsen/stolon/client.clj, postgres-rds/).

One client class speaks every bundled SQL workload over the
from-scratch wire protocol in ``_postgres.py``:

- register r/w/cas, set add/read, Elle list-append txns — the surface
  the postgres suite established (suites/postgres.py)
- bank read/transfer (cockroach/bank.clj shape: serializable two-row
  transfers with overdraft refusal)
- dirty-reads read/write (galera/dirty_reads.clj shape)
- monotonic inc/read-all (cockroach/monotonic.clj:32-66: read max,
  insert max+1 with the DB's own timestamp expression — cockroach's
  ``cluster_logical_timestamp()``, plain postgres's wall clock)
- sequential write/read (cockroach/sequential.clj:33-95: subkeys
  inserted in order across per-hash tables, read reversed)

Error discipline: SQLSTATE class-40 rollbacks (serialization failure /
deadlock) are definite ``fail``; network errors fail reads and are
indeterminate for writes; an errored connection is rebuilt before its
next use (leftover bytes would desync the wire protocol).
"""
from __future__ import annotations

import zlib

from jepsen_tpu.client import Client
from jepsen_tpu.suites._postgres import (DEADLOCK_DETECTED, PGConnection,
                                         PgError, SERIALIZATION_FAILURE,
                                         UNDEFINED_TABLE, parse_int_array)

SEQ_TABLE_COUNT = 5
COMMENT_TABLE_COUNT = 10  # cockroach/comments.clj:30 table-count
# postgres wall-clock default; cockroach overrides with its HLC
DEFAULT_TS_EXPR = "extract(epoch from clock_timestamp())"


def seq_table(k: str, table_count: int = SEQ_TABLE_COUNT) -> str:
    """Stable subkey→table assignment (sequential.clj:41-44; crc32, not
    Python's salted hash, so every client agrees)."""
    return f"seq_{zlib.crc32(str(k).encode()) % table_count}"


class PGSuiteClient(Client):
    """Workload client over one PGConnection. ``ts_expr`` is the SQL
    expression for the monotonic workload's commit-order timestamp;
    ``endpoint_mode`` is "node" (connect to your own node) or "first"
    (all clients share node 1)."""

    def __init__(self, *, port: int = 5432, database: str = "jepsen",
                 user: str = "jepsen", password: str = "jepsenpw",
                 isolation: str = "serializable",
                 endpoint_mode: str = "node", txn_style: str = "append",
                 ts_expr: str = DEFAULT_TS_EXPR,
                 logical_ts: bool = False,
                 timeout_s: float = 10.0, node: str | None = None):
        self.port = port
        self.database = database
        self.user = user
        self.password = password
        self.isolation = isolation
        self.endpoint_mode = endpoint_mode
        # "append": txn r micro-ops read the lists table (Elle
        # list-append); "wr": they read registers (Elle rw-register)
        self.txn_style = txn_style
        self.ts_expr = ts_expr
        # wall-clock ts_exprs (the clock_timestamp() default) make the
        # monotonic workload meaningless under a clock nemesis — the
        # checker downgrades to "unknown" in that combination. Suites with
        # a logical/HLC expression (cockroach) set logical_ts=True.
        self.logical_ts = logical_ts
        self.timeout_s = timeout_s
        self.node = node
        self.conn: PGConnection | None = None
        self._broken = False

    # -- lifecycle --------------------------------------------------------

    def endpoint(self, test, node) -> tuple[str, int]:
        if self.endpoint_mode == "first":
            return (test.get("nodes") or [node])[0], self.port
        return node, self.port

    def _connect(self, test):
        host, port = self.endpoint(test, self.node)
        self.conn = PGConnection(
            host=host, port=port, database=self.database, user=self.user,
            password=self.password, timeout_s=self.timeout_s)

    def open(self, test, node):
        c = type(self)(port=self.port, database=self.database,
                       user=self.user, password=self.password,
                       isolation=self.isolation,
                       endpoint_mode=self.endpoint_mode,
                       txn_style=self.txn_style, ts_expr=self.ts_expr,
                       logical_ts=self.logical_ts,
                       timeout_s=self.timeout_s, node=node)
        c._connect(test)
        return c

    def setup(self, test):
        ddl = [
            "CREATE TABLE IF NOT EXISTS registers "
            "(k INT PRIMARY KEY, v BIGINT)",
            "CREATE TABLE IF NOT EXISTS sets (elem BIGINT PRIMARY KEY)",
            "CREATE TABLE IF NOT EXISTS lists "
            "(k INT PRIMARY KEY, elems INT[] NOT NULL DEFAULT '{}')",
            "CREATE TABLE IF NOT EXISTS accounts "
            "(id INT PRIMARY KEY, balance BIGINT NOT NULL)",
            "CREATE TABLE IF NOT EXISTS dirty "
            "(id INT PRIMARY KEY, x BIGINT NOT NULL)",
            "CREATE TABLE IF NOT EXISTS mono "
            "(val BIGINT, sts TEXT, node TEXT, process INT)",
            "CREATE TABLE IF NOT EXISTS adya "
            "(pair INT, cell TEXT, uid BIGINT, PRIMARY KEY (pair, cell))",
            "CREATE TABLE IF NOT EXISTS counters "
            "(id INT PRIMARY KEY, v BIGINT NOT NULL)",
        ]
        ddl += [f"CREATE TABLE IF NOT EXISTS seq_{i} "
                f"(k TEXT PRIMARY KEY)" for i in range(SEQ_TABLE_COUNT)]
        # comments workload: blind inserts split across tables so ids
        # land in different shard ranges (cockroach/comments.clj:30-40)
        ddl += [f"CREATE TABLE IF NOT EXISTS comment_{i} "
                f"(id INT PRIMARY KEY, key INT)"
                for i in range(COMMENT_TABLE_COUNT)]
        for stmt in ddl:
            self.conn.query(stmt)
        for a in test.get("accounts", []):
            self.conn.query(
                f"INSERT INTO accounts (id, balance) VALUES ({int(a)}, 10) "
                f"ON CONFLICT DO NOTHING")
        for i in range(int(test.get("dirty-rows", 0) or 0)):
            self.conn.query(
                f"INSERT INTO dirty (id, x) VALUES ({int(i)}, -1) "
                f"ON CONFLICT DO NOTHING")
        if test.get("counter"):
            self.conn.query("INSERT INTO counters (id, v) VALUES (0, 0) "
                            "ON CONFLICT DO NOTHING")
        if test.get("ledger"):
            # one row per transfer, indexed by account (ledger.clj:85-99)
            self.conn.query(
                "CREATE TABLE IF NOT EXISTS ledger "
                "(id INT PRIMARY KEY, account INT NOT NULL, "
                "amount INT NOT NULL)")
            self.conn.query(
                "CREATE INDEX IF NOT EXISTS i_account ON ledger (account)")

    def close(self, test):
        if self.conn is not None:
            try:
                self.conn.close()
            except Exception:  # noqa: BLE001
                pass

    # -- transactions -----------------------------------------------------

    def _begin(self):
        level = self.isolation.upper().replace("-", " ")
        self.conn.query(f"BEGIN ISOLATION LEVEL {level}")

    def _rollback(self):
        try:
            self.conn.query("ROLLBACK")
        except (PgError, OSError):
            self._broken = True

    def _select_int(self, sql: str):
        rows, _ = self.conn.query(sql)
        if not rows or rows[0][0] is None:
            return None
        return int(rows[0][0])

    def _sql_error(self, op, e: PgError):
        if e.sqlstate in (SERIALIZATION_FAILURE, DEADLOCK_DETECTED):
            return {**op, "type": "fail",
                    "error": ["serialization-failure", e.msg]}
        kind = "fail" if op.get("f") in ("read", "read-all") else "info"
        return {**op, "type": kind, "error": ["sql", e.sqlstate, e.msg]}

    # -- op dispatch ------------------------------------------------------

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        if self._broken:
            self.close(test)
            self._connect(test)
            self._broken = False
        try:
            if test.get("counter") and f == "add":
                _, tag = self.conn.query(
                    f"UPDATE counters SET v = v + {int(v)} WHERE id = 0")
                if self.conn.rowcount(tag) != 1:
                    # row absent: the add definitely did not apply — an
                    # ok here would fabricate acknowledged increments
                    return {**op, "type": "fail", "error": ["no-counter-row"]}
                return {**op, "type": "ok"}
            if test.get("counter") and f == "read" and v is None:
                val = self._select_int("SELECT v FROM counters WHERE id = 0")
                return {**op, "type": "ok", "value": int(val or 0)}
            if test.get("comments") and f == "write":
                k, i = v
                t = int(i) % COMMENT_TABLE_COUNT
                self.conn.query(
                    f"INSERT INTO comment_{t} (id, key) "
                    f"VALUES ({int(i)}, {int(k)})")
                return {**op, "type": "ok"}
            if test.get("comments") and f == "read":
                k, _ = v
                # one txn over all tables (comments.clj:74-84 reads both
                # tables in a transaction so visibility is a snapshot)
                self._begin()
                try:
                    ids: list = []
                    for t in range(COMMENT_TABLE_COUNT):
                        rows, _tag = self.conn.query(
                            f"SELECT id FROM comment_{t} "
                            f"WHERE key = {int(k)}")
                        ids += [int(r[0]) for r in rows]
                    self.conn.query("COMMIT")
                except PgError as e:
                    self._rollback()
                    return self._sql_error(op, e)
                return {**op, "type": "ok", "value": [k, sorted(ids)]}
            if f == "txn":
                return self._txn(op)
            if f == "add":
                self.conn.query(
                    f"INSERT INTO sets (elem) VALUES ({int(v)}) "
                    f"ON CONFLICT DO NOTHING")
                return {**op, "type": "ok"}
            if f == "read" and v is None:
                return self._whole_read(test, op)
            if f == "read" and isinstance(v, (list, tuple)):
                k, _ = v
                val = self._select_int(
                    f"SELECT v FROM registers WHERE k = {int(k)}")
                return {**op, "type": "ok", "value": [k, val]}
            if f == "read":
                return self._seq_read(test, op)
            if f == "write" and isinstance(v, (list, tuple)):
                k, val = v
                self.conn.query(
                    f"INSERT INTO registers (k, v) VALUES ({int(k)}, "
                    f"{int(val)}) ON CONFLICT (k) DO UPDATE "
                    f"SET v = {int(val)}")
                return {**op, "type": "ok"}
            if f == "write" and test.get("key-count"):
                return self._seq_write(test, op)
            if f == "write":
                return self._dirty_write(test, op)
            if f == "cas":
                k, (old, new) = v
                _, tag = self.conn.query(
                    f"UPDATE registers SET v = {int(new)} "
                    f"WHERE k = {int(k)} AND v = {int(old)}")
                ok = self.conn.rowcount(tag) == 1
                return {**op, "type": "ok" if ok else "fail"}
            if test.get("ledger") and f == "transfer":
                return self._ledger_transfer(test, op)
            if f == "transfer":
                return self._transfer(op)
            if f == "insert":
                return self._adya_insert(op)
            if f == "inc":
                return self._mono_inc(test, op)
            if f == "read-all":
                # ts stays a string: cockroach HLCs overflow float
                # precision; the checker compares them as Decimals
                rows, _ = self.conn.query(
                    "SELECT val, sts FROM mono ORDER BY sts::numeric")
                return {**op, "type": "ok",
                        "value": [[int(r[0]), r[1]] for r in rows]}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except PgError as e:
            return self._sql_error(op, e)
        except (TimeoutError, ConnectionError, OSError) as e:
            self._broken = True
            kind = "fail" if f in ("read", "read-all") else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    # -- workload bodies --------------------------------------------------

    def _whole_read(self, test, op):
        if test.get("accounts"):
            rows, _ = self.conn.query(
                "SELECT id, balance FROM accounts ORDER BY id")
            return {**op, "type": "ok",
                    "value": {int(r[0]): int(r[1]) for r in rows}}
        if test.get("dirty-rows"):
            rows, _ = self.conn.query("SELECT x FROM dirty ORDER BY id")
            return {**op, "type": "ok",
                    "value": [int(r[0]) for r in rows]}
        rows, _ = self.conn.query("SELECT elem FROM sets ORDER BY elem")
        return {**op, "type": "ok", "value": [int(r[0]) for r in rows]}

    def _txn(self, op):
        if self.txn_style == "append-table":
            return self._txn_append_table(op)
        self._begin()
        out = []
        try:
            for f, k, v in op.get("value") or []:
                if f == "r" and self.txn_style == "wr":
                    val = self._select_int(
                        f"SELECT v FROM registers WHERE k = {int(k)}")
                    out.append(["r", k, val])
                elif f == "r":
                    rows, _ = self.conn.query(
                        f"SELECT elems FROM lists WHERE k = {int(k)}")
                    out.append(["r", k,
                                parse_int_array(rows[0][0]) if rows else []])
                elif f == "append":
                    self.conn.query(
                        f"INSERT INTO lists (k, elems) VALUES ({int(k)}, "
                        f"ARRAY[{int(v)}]) ON CONFLICT (k) DO UPDATE "
                        f"SET elems = lists.elems || {int(v)}")
                    out.append(["append", k, v])
                elif f == "w":
                    self.conn.query(
                        f"INSERT INTO registers (k, v) VALUES ({int(k)}, "
                        f"{int(v)}) ON CONFLICT (k) DO UPDATE "
                        f"SET v = {int(v)}")
                    out.append(["w", k, v])
                else:
                    raise ValueError(f"unknown micro-op {f!r}")
            self.conn.query("COMMIT")
            return {**op, "type": "ok", "value": out}
        except PgError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _txn_append_table(self, op):
        """Elle list-append with one table per key: rows are the list
        elements, ordered by an insert-timestamp column, and tables are
        created on demand when a txn trips "relation does not exist" —
        then the whole txn retries
        (yugabyte/ysql/append_table.clj:28-129; its docstring concedes
        the timestamp ordering is best-effort, and so is this)."""
        last_err = None
        for _ in range(8):
            self._begin()
            out = []
            try:
                for f, k, v in op.get("value") or []:
                    table = f"append_{int(k)}"
                    if f == "r":
                        rows, _ = self.conn.query(
                            f"SELECT v FROM {table} ORDER BY k")
                        out.append(["r", k, [int(r[0]) for r in rows]])
                    elif f == "append":
                        self.conn.query(
                            f"INSERT INTO {table} (v) VALUES ({int(v)})")
                        out.append(["append", k, v])
                    else:
                        raise ValueError(f"unknown micro-op {f!r}")
                self.conn.query("COMMIT")
                return {**op, "type": "ok", "value": out}
            except PgError as e:
                self._rollback()
                if e.sqlstate != UNDEFINED_TABLE:
                    return self._sql_error(op, e)
                last_err = e
                table = self._missing_relation(e)
                if not table:
                    return self._sql_error(op, e)
                try:  # YB chokes on IF NOT EXISTS races: swallow dups
                    # clock_timestamp(), not now(): now() is fixed for
                    # the whole txn, so two same-key appends in one txn
                    # would tie on k and read back in arbitrary order —
                    # a guaranteed false Elle anomaly, not the conceded
                    # best-effort cross-txn skew
                    self.conn.query(
                        f"CREATE TABLE IF NOT EXISTS {table} "
                        f"(k TIMESTAMP DEFAULT clock_timestamp(), v INT)")
                except PgError:
                    pass
        return self._sql_error(op, last_err)

    @staticmethod
    def _missing_relation(e: PgError) -> str | None:
        """The quoted relation name out of a 42P01 message
        (append_table.clj:92-101 catch-dne) — only when it has the
        append-table shape; anything else (schema-qualified, some other
        relation) must NOT be interpolated into CREATE TABLE DDL."""
        import re
        m = re.search(r'relation "(append_\d+)" does not exist',
                      e.msg or "")
        return m.group(1) if m else None

    def _ledger_transfer(self, test, op):
        """Row-per-transfer ledger insert (ledger.clj:56-68,117-132):
        deposits insert unconditionally; withdrawals first sum the
        account's OTHER rows and only insert while the total stays
        non-negative — the guard a write-skewing DB lets two concurrent
        withdrawals both pass."""
        account, amount, row_id = (list(op.get("value") or []) + [0, 0, 0])[:3]
        account, amount, row_id = int(account), int(amount), int(row_id)
        self._begin()
        try:
            if amount <= 0:
                balance = self._select_int(
                    f"SELECT COALESCE(SUM(amount), 0) FROM ledger "
                    f"WHERE account = {account} AND id != {row_id}") or 0
                if balance + amount < 0:
                    self._rollback()
                    return {**op, "type": "fail",
                            "error": ["insufficient", balance]}
            self.conn.query(
                f"INSERT INTO ledger (id, account, amount) "
                f"VALUES ({row_id}, {account}, {amount})")
            self.conn.query("COMMIT")
            return {**op, "type": "ok"}
        except PgError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _transfer(self, op):
        t = op.get("value") or {}
        frm, to = int(t.get("from")), int(t.get("to"))
        amount = int(t.get("amount", 0))
        self._begin()
        try:
            b1 = self._select_int(
                f"SELECT balance FROM accounts WHERE id = {frm}")
            b2 = self._select_int(
                f"SELECT balance FROM accounts WHERE id = {to}")
            if b1 is None or b2 is None:
                self._rollback()
                return {**op, "type": "fail", "error": ["no-such-account"]}
            if b1 - amount < 0:
                self._rollback()
                return {**op, "type": "fail",
                        "error": ["negative", frm, b1 - amount]}
            self.conn.query(f"UPDATE accounts SET balance = {b1 - amount} "
                            f"WHERE id = {frm}")
            self.conn.query(f"UPDATE accounts SET balance = {b2 + amount} "
                            f"WHERE id = {to}")
            self.conn.query("COMMIT")
            return {**op, "type": "ok"}
        except PgError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _dirty_write(self, test, op):
        x = int(op.get("value"))
        n = int(test.get("dirty-rows", 4) or 4)
        self._begin()
        try:
            for i in range(n):
                self.conn.query(f"SELECT x FROM dirty WHERE id = {i}")
            for i in range(n):
                self.conn.query(f"UPDATE dirty SET x = {x} WHERE id = {i}")
            self.conn.query("COMMIT")
            return {**op, "type": "ok"}
        except PgError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _adya_insert(self, op):
        """Adya G2 txn (tests/adya.clj:12-59 via workloads/adya.py):
        predicate-read both cells of the pair; insert our uid only if
        both are empty. Serializability must abort one of two racing
        inserts — two ok inserts per pair demonstrate G2."""
        pair, uid, cell = op.get("value")
        self._begin()
        try:
            rows, _ = self.conn.query(
                f"SELECT uid FROM adya WHERE pair = {int(pair)}")
            if rows:
                self._rollback()
                return {**op, "type": "fail", "error": ["pair-occupied"]}
            self.conn.query(
                f"INSERT INTO adya (pair, cell, uid) VALUES "
                f"({int(pair)}, '{cell}', {int(uid)})")
            self.conn.query("COMMIT")
            return {**op, "type": "ok"}
        except PgError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _mono_inc(self, test, op):
        """Read max, insert max+1 with the DB's timestamp expression in
        one serializable txn (monotonic.clj:32-66)."""
        self._begin()
        try:
            m = self._select_int("SELECT MAX(val) FROM mono")
            val = (m if m is not None else -1) + 1
            self.conn.query(
                f"INSERT INTO mono (val, sts, node, process) VALUES "
                f"({val}, ({self.ts_expr})::text, "
                f"'{self.node}', {int(op.get('process') or 0)})")
            self.conn.query("COMMIT")
            return {**op, "type": "ok", "value": val}
        except PgError as e:
            self._rollback()
            return self._sql_error(op, e)

    def _seq_write(self, test, op):
        """Insert each subkey in client order, one txn each
        (sequential.clj:76-82)."""
        from jepsen_tpu.workloads.sequential import subkeys
        for sk in subkeys(int(test.get("key-count", 5)), op.get("value")):
            self.conn.query(
                f"INSERT INTO {seq_table(sk)} (k) VALUES ('{sk}') "
                f"ON CONFLICT DO NOTHING")
        return {**op, "type": "ok"}

    def _seq_read(self, test, op):
        """Read subkeys reversed (sequential.clj:84-95)."""
        from jepsen_tpu.workloads.sequential import subkeys
        ks = subkeys(int(test.get("key-count", 5)), op.get("value"))
        out = []
        for sk in reversed(ks):
            rows, _ = self.conn.query(
                f"SELECT k FROM {seq_table(sk)} WHERE k = '{sk}'")
            out.append(rows[0][0] if rows else None)
        return {**op, "type": "ok", "value": [op.get("value"), out]}
