"""Minimal PostgreSQL v3 wire-protocol client over stdlib sockets.

The reference's postgres-family suites (postgres-rds/src/jepsen/
postgres_rds.clj, stolon/src/jepsen/stolon.clj, cockroachdb/src/jepsen/
cockroach.clj, yugabyte/src/yugabyte/ysql.clj) all ride the JVM jdbc/
postgresql driver; this module is the TPU-framework equivalent wire
client so those suites need no third-party Python driver.

Implements the subset every suite needs: the startup handshake with
trust / cleartext / md5 / SCRAM-SHA-256 auth, the simple-query protocol
with text-format resultsets, error surfacing with SQLSTATE, and clean
termination. Row cells come back as Python strings (or None for SQL
NULL) — callers cast; ``parse_int_array`` handles ``int[]`` columns.
No extended protocol, no COPY, no TLS: test rigs connect over the
cluster's private network exactly like the reference's conn-specs.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct

PROTOCOL_V3 = 196608  # 3 << 16


class PgError(Exception):
    """Server ErrorResponse: ``.sqlstate``, ``.severity``, ``.msg``."""

    def __init__(self, fields: dict):
        self.severity = fields.get("S", "ERROR")
        self.sqlstate = fields.get("C", "")
        self.msg = fields.get("M", "")
        super().__init__(f"[{self.sqlstate}] {self.msg}")


# SQLSTATEs every retry loop cares about (class 40 = txn rollback)
SERIALIZATION_FAILURE = "40001"
DEADLOCK_DETECTED = "40P01"
UNDEFINED_TABLE = "42P01"


def parse_int_array(text: str | None) -> list[int]:
    """``'{1,2,3}'`` → ``[1, 2, 3]`` (text-format int[] columns)."""
    if not text or text == "{}":
        return []
    return [int(x) for x in text.strip("{}").split(",")]


def _scram_client(password: str, server_first: str, client_first_bare: str,
                  ) -> tuple[str, bytes]:
    """Computes the SCRAM-SHA-256 client-final message and ServerKey
    (RFC 5802/7677) from the server-first challenge."""
    parts = dict(kv.split("=", 1) for kv in server_first.split(","))
    nonce, salt_b64, iters = parts["r"], parts["s"], int(parts["i"])
    salted = hashlib.pbkdf2_hmac("sha256", password.encode(),
                                 base64.b64decode(salt_b64), iters)
    client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
    stored_key = hashlib.sha256(client_key).digest()
    without_proof = f"c=biws,r={nonce}"
    auth_message = ",".join([client_first_bare, server_first,
                             without_proof]).encode()
    signature = hmac.new(stored_key, auth_message, hashlib.sha256).digest()
    proof = bytes(a ^ b for a, b in zip(client_key, signature))
    server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
    expect_sig = hmac.new(server_key, auth_message, hashlib.sha256).digest()
    final = f"{without_proof},p={base64.b64encode(proof).decode()}"
    return final, expect_sig


class PGConnection:
    """One authenticated connection; ``query`` returns (rows, tag)."""

    def __init__(self, host: str, port: int = 5432, user: str = "postgres",
                 password: str = "", database: str = "postgres",
                 timeout_s: float = 10.0):
        self.host, self.port = host, port
        self.parameters: dict[str, str] = {}
        self.txn_status = b"I"
        self.sock = socket.create_connection((host, port), timeout=timeout_s)
        try:
            self._startup(user, password, database)
        except BaseException:
            self.sock.close()
            raise

    # -- framing: backend messages are type byte + int32 length -----------

    def _recv_exact(self, n: int) -> bytes:
        from jepsen_tpu.suites._wire import recv_exact
        return recv_exact(self.sock, n)

    def _read_message(self) -> tuple[bytes, bytes]:
        header = self._recv_exact(5)
        mtype = header[:1]
        length = struct.unpack("!I", header[1:])[0]
        return mtype, self._recv_exact(length - 4)

    def _send(self, mtype: bytes, payload: bytes) -> None:
        self.sock.sendall(mtype + struct.pack("!I", len(payload) + 4)
                          + payload)

    @staticmethod
    def _error_fields(payload: bytes) -> dict:
        fields = {}
        pos = 0
        while pos < len(payload) and payload[pos] != 0:
            code = chr(payload[pos])
            end = payload.index(b"\x00", pos + 1)
            fields[code] = payload[pos + 1:end].decode("utf8", "replace")
            pos = end + 1
        return fields

    # -- startup / auth ---------------------------------------------------

    def _startup(self, user: str, password: str, database: str) -> None:
        kv = (f"user\x00{user}\x00database\x00{database}\x00"
              "application_name\x00jepsen-tpu\x00\x00").encode()
        payload = struct.pack("!I", PROTOCOL_V3) + kv
        self.sock.sendall(struct.pack("!I", len(payload) + 4) + payload)

        scram_expect_sig = None
        while True:
            mtype, body = self._read_message()
            if mtype == b"E":
                raise PgError(self._error_fields(body))
            if mtype == b"R":
                code = struct.unpack_from("!I", body)[0]
                if code == 0:  # AuthenticationOk
                    continue
                if code == 3:  # CleartextPassword
                    self._send(b"p", password.encode() + b"\x00")
                elif code == 5:  # MD5Password
                    salt = body[4:8]
                    inner = hashlib.md5(
                        password.encode() + user.encode()).hexdigest()
                    digest = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._send(b"p", b"md5" + digest.encode() + b"\x00")
                elif code == 10:  # SASL: mechanism list
                    mechs = body[4:].split(b"\x00")
                    if b"SCRAM-SHA-256" not in mechs:
                        raise ConnectionError(
                            f"no supported SASL mechanism in {mechs!r}")
                    self._scram_bare = (
                        "n=,r=" + base64.b64encode(os.urandom(18)).decode())
                    first = ("n,," + self._scram_bare).encode()
                    self._send(b"p", b"SCRAM-SHA-256\x00"
                               + struct.pack("!I", len(first)) + first)
                elif code == 11:  # SASLContinue
                    server_first = body[4:].decode()
                    final, scram_expect_sig = _scram_client(
                        password, server_first, self._scram_bare)
                    self._send(b"p", final.encode())
                elif code == 12:  # SASLFinal
                    fields = dict(kv.split("=", 1) for kv in
                                  body[4:].decode().split(","))
                    if scram_expect_sig is not None and base64.b64decode(
                            fields.get("v", "")) != scram_expect_sig:
                        raise ConnectionError(
                            "SCRAM server signature mismatch")
                else:
                    raise ConnectionError(
                        f"unsupported postgres auth method {code}")
            elif mtype == b"S":  # ParameterStatus
                k, v = body.split(b"\x00")[:2]
                self.parameters[k.decode()] = v.decode()
            elif mtype == b"K":  # BackendKeyData
                pass
            elif mtype == b"Z":  # ReadyForQuery
                self.txn_status = body[:1]
                return
            elif mtype == b"N":  # NoticeResponse
                pass
            else:
                raise ConnectionError(
                    f"unexpected startup message {mtype!r}")

    # -- simple query protocol --------------------------------------------

    def query(self, sql: str):
        """Runs one statement (simple-query protocol). Resultset → (rows,
        command tag) with rows as tuples of str|None; statements without
        a resultset → ([], tag). Raises PgError on server error (the
        connection stays usable — the protocol resyncs on ReadyForQuery).
        """
        self._send(b"Q", sql.encode() + b"\x00")
        rows: list[tuple] = []
        tag = ""
        error: dict | None = None
        while True:
            mtype, body = self._read_message()
            if mtype == b"T":  # RowDescription: column metadata, skipped
                pass
            elif mtype == b"D":
                ncols = struct.unpack_from("!H", body)[0]
                pos, row = 2, []
                for _ in range(ncols):
                    n = struct.unpack_from("!i", body, pos)[0]
                    pos += 4
                    if n == -1:
                        row.append(None)
                    else:
                        row.append(body[pos:pos + n].decode("utf8",
                                                            "replace"))
                        pos += n
                rows.append(tuple(row))
            elif mtype == b"C":
                tag = body.rstrip(b"\x00").decode()
            elif mtype == b"E":
                error = self._error_fields(body)
            elif mtype in (b"N", b"S", b"I"):  # notice/param/empty-query
                pass
            elif mtype == b"Z":
                self.txn_status = body[:1]
                if error is not None:
                    raise PgError(error)
                return rows, tag

    def rowcount(self, tag: str) -> int:
        """Affected-row count from a command tag (``'UPDATE 1'`` → 1)."""
        parts = tag.rsplit(" ", 1)
        try:
            return int(parts[-1])
        except (ValueError, IndexError):
            return 0

    def close(self) -> None:
        try:
            self._send(b"X", b"")  # Terminate
        except OSError:
            pass
        finally:
            self.sock.close()
