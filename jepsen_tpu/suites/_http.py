"""Tiny stdlib HTTP/JSON helper shared by the HTTP-API DB suites
(elasticsearch, crate, dgraph, ignite, hazelcast, chronos — the suites
whose reference counterparts ride JVM HTTP clients, e.g.
crate/src/jepsen/crate/core.clj, chronos/src/jepsen/chronos.clj:28-31).

Network-level failures surface as the stdlib exceptions
(``urllib.error.URLError``, ``TimeoutError``, ``ConnectionError``) so
each client's invoke can map them onto ``fail``/``info`` completions."""
from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request

NET_ERRORS = (TimeoutError, urllib.error.URLError, ConnectionError, OSError)


def http_json(url: str, body=None, *, method: str | None = None,
              timeout_s: float = 5.0, headers: dict | None = None,
              raw_body: bytes | None = None):
    """One request; JSON (or raw text on non-JSON) response body.

    ``body`` is JSON-encoded when given; ``raw_body`` sends bytes as-is.
    4xx/5xx raise ``urllib.error.HTTPError`` (response body preserved on
    ``.read()`` — callers that need error JSON use ``http_error_json``)."""
    data = raw_body
    hdrs = dict(headers or {})
    if body is not None:
        data = json.dumps(body).encode()
        hdrs.setdefault("Content-Type", "application/json")
    req = urllib.request.Request(
        url, data=data, headers=hdrs,
        method=method or ("POST" if data is not None else "GET"))
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        text = resp.read().decode()
    if not text:
        return None
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        return text


def http_error_json(err: urllib.error.HTTPError):
    """The JSON body of an HTTPError, or None."""
    try:
        return json.loads(err.read().decode())
    except Exception:
        return None


def quote(s) -> str:
    return urllib.parse.quote(str(s), safe="")
