"""Consul test suite (reference: consul/ suite in jaydenwen123/jepsen —
consul/src/jepsen/system/consul.clj: a 5-node consul server cluster
tested through its HTTP KV API).

The client speaks Consul's KV HTTP API with stdlib urllib: reads use
``?consistent`` (linearizable through the raft leader), writes are plain
PUTs, and compare-and-set uses the ``?cas=<ModifyIndex>`` protocol —
read the key's ModifyIndex, then PUT conditional on it. Set adds map to
a key directory listed with ``?keys``.

DB automation installs the consul binary zip on each node and runs
``consul agent -server -bootstrap-expect N`` with retry-join at the
first node, the same bring-up the reference automates.
"""
from __future__ import annotations

import base64
import json
import logging
import urllib.error
import urllib.parse
import urllib.request

from jepsen_tpu import cli, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)

logger = logging.getLogger("jepsen.consul")

DEFAULT_VERSION = "1.18.2"
DIR = "/opt/consul"
DATA_DIR = f"{DIR}/data"
LOG_FILE = f"{DIR}/consul.log"
PIDFILE = f"{DIR}/consul.pid"
HTTP_PORT = 8500


def archive_url(version: str) -> str:
    return (f"https://releases.hashicorp.com/consul/{version}/"
            f"consul_{version}_linux_amd64.zip")


class ConsulDB(db_mod.DB, db_mod.Process, db_mod.Pause, db_mod.LogFiles):
    """consul agent -server lifecycle (reference consul.clj start-consul!)."""

    def __init__(self, version: str = DEFAULT_VERSION):
        self.version = version

    def setup(self, test, node):
        logger.info("%s: installing consul %s", node, self.version)
        cu.install_archive(archive_url(self.version), DIR)
        self.start(test, node)
        cu.await_tcp_port(HTTP_PORT, host=node)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DATA_DIR)
        cu.rm_rf(LOG_FILE)

    def start(self, test, node):
        nodes = test.get("nodes") or []
        return cu.start_daemon(
            {"logfile": LOG_FILE, "pidfile": PIDFILE, "chdir": DIR},
            f"{DIR}/consul", "agent", "-server",
            "-node", node,
            "-data-dir", DATA_DIR,
            "-bind", "0.0.0.0",
            "-client", "0.0.0.0",
            "-bootstrap-expect", str(len(nodes)),
            "-retry-join", nodes[0] if nodes else node,
        )

    def kill(self, test, node):
        cu.stop_daemon(f"{DIR}/consul", PIDFILE)
        cu.grepkill("consul")

    def pause(self, test, node):
        cu.grepkill("consul", sig="STOP")

    def resume(self, test, node):
        cu.grepkill("consul", sig="CONT")

    def log_files(self, test, node):
        return [LOG_FILE]


class ConsulClient(Client):
    """KV r/w/cas over Consul's HTTP API. Register ops arrive
    independent-lifted ([k, v] tuples); CAS uses the ModifyIndex
    ``?cas=`` protocol, so a lost race is a definite ``fail``."""

    def __init__(self, prefix: str = "jepsen", timeout_s: float = 5.0,
                 node: str | None = None):
        self.prefix = prefix
        self.timeout_s = timeout_s
        self.node = node

    def open(self, test, node):
        return ConsulClient(self.prefix, self.timeout_s, node)

    def _url(self, path: str, **params) -> str:
        q = f"?{urllib.parse.urlencode(params)}" if params else ""
        return f"http://{self.node}:{HTTP_PORT}/v1/kv/{urllib.parse.quote(path)}{q}"

    def _request(self, url: str, body: bytes | None = None,
                 method: str = "GET"):
        req = urllib.request.Request(url, data=body, method=method)
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode())

    def _read_entry(self, k):
        """(value, modify_index) or (None, 0) when absent."""
        try:
            doc = self._request(self._url(f"{self.prefix}/{k}",
                                          consistent="true"))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None, 0
            raise
        entry = doc[0]
        raw = base64.b64decode(entry["Value"] or b"").decode()
        return (int(raw) if raw else None), int(entry["ModifyIndex"])

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "add":
                self._request(self._url(f"{self.prefix}-set/{v}"),
                              str(v).encode(), method="PUT")
                return {**op, "type": "ok"}
            if f == "read" and v is None:  # whole-set read
                try:
                    keys = self._request(self._url(f"{self.prefix}-set/",
                                                   keys="true",
                                                   consistent="true"))
                    elems = sorted(int(k.rsplit("/", 1)[-1]) for k in keys)
                except urllib.error.HTTPError as e:
                    if e.code != 404:
                        raise
                    elems = []
                return {**op, "type": "ok", "value": elems}
            if f == "read":
                k, _ = v
                value, _idx = self._read_entry(k)
                return {**op, "type": "ok", "value": [k, value]}
            if f == "write":
                k, val = v
                self._request(self._url(f"{self.prefix}/{k}"),
                              str(val).encode(), method="PUT")
                return {**op, "type": "ok"}
            if f == "cas":
                k, (old, new) = v
                current, idx = self._read_entry(k)
                if current != old:
                    return {**op, "type": "fail"}
                applied = self._request(self._url(f"{self.prefix}/{k}",
                                                  cas=str(idx)),
                                        str(new).encode(), method="PUT")
                return {**op, "type": "ok" if applied else "fail"}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except urllib.error.HTTPError as e:
            # consul answers 500 "No cluster leader" during faults
            if e.code >= 500:
                kind = "fail" if f == "read" else "info"
                return {**op, "type": kind, "error": ["http", e.code]}
            raise
        except (TimeoutError, urllib.error.URLError, ConnectionError, OSError) as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}

    def close(self, test):
        pass


SUPPORTED_WORKLOADS = ("register", "set")


def consul_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="consul", supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": ConsulDB(o.get("version", DEFAULT_VERSION)),
                             "client": ConsulClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(consul_test, extra_keys=("version",)),
    standard_opt_fn(SUPPORTED_WORKLOADS,
                    extra=lambda p: p.add_argument(
                        "--version", default=DEFAULT_VERSION)),
    name="jepsen-consul")


if __name__ == "__main__":
    import sys
    sys.exit(main())
