"""RobustIRC test suite (reference: robustirc/src/jepsen/robustirc.clj
— a raft-replicated IRC network whose HTTP "robustsession" API lets a
set test ride IRC TOPIC messages: adds set the channel topic to an
integer, the final read replays the message log and collects every
TOPIC value, checked with set semantics).

The client speaks the robustsession JSON API over HTTPS with the
server's self-signed cert (robustirc.clj:104-136): POST /robustirc/v1/
session to open, POST .../{sid}/message with an X-Session-Auth header
to send an IRC line, GET .../{sid}/messages?lastseen=0.0 to stream the
log back.

DB automation per robustirc.clj:24-103: build the Go binary, upload a
shared self-signed cert, start the primary with ``-singlenode``, then
join the rest with ``-join``.
"""
from __future__ import annotations

import hashlib
import json
import logging
import ssl
import threading
import urllib.request
import uuid

from jepsen_tpu import cli, control, db as db_mod
from jepsen_tpu.client import Client
from jepsen_tpu.control import util as cu
from jepsen_tpu.os_setup import Debian
from jepsen_tpu.suites import (build_suite_test, standard_opt_fn,
                               standard_test_fn)
from jepsen_tpu.suites._http import NET_ERRORS

logger = logging.getLogger("jepsen.robustirc")

PORT = 13001
NETWORK_PASSWORD = "secret"
CERT = "/tmp/cert.pem"
KEY = "/tmp/key.pem"
BINARY = "/root/gocode/bin/robustirc"
DATA_DIR = "/var/lib/robustirc"
CHANNEL = "#jepsen"


def base_args(node: str) -> list[str]:
    return [f"-listen={node}:{PORT}",
            f"-network_password={NETWORK_PASSWORD}",
            "-network_name=jepsen",
            f"-tls_cert_path={CERT}",
            f"-tls_ca_file={CERT}",
            f"-tls_key_path={KEY}"]


def shared_cert(test: dict) -> tuple[str, str]:
    """Generates (once per test, on the control node) a self-signed cert
    whose SAN covers every node, for upload to the whole cluster."""
    import subprocess
    import tempfile
    lock = test.setdefault("_robustirc_cert_lock", threading.Lock())
    with lock:
        paths = test.get("_robustirc_cert")
        if paths:
            return paths
        d = tempfile.mkdtemp(prefix="jepsen-robustirc-")
        cert, key = f"{d}/cert.pem", f"{d}/key.pem"
        san = ",".join(f"DNS:{n}" for n in (test.get("nodes") or []))
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "7",
             "-subj", "/CN=jepsen", "-addext", f"subjectAltName={san}"],
            check=True, capture_output=True)
        test["_robustirc_cert"] = (cert, key)
        return cert, key


class RobustIRCDB(db_mod.DB, db_mod.Process, db_mod.LogFiles):
    """Go build + singlenode bootstrap + joins (robustirc.clj:24-103)."""

    def setup(self, test, node):
        from jepsen_tpu import core, os_setup
        os_setup.install(["golang-go", "git", "openssl"])
        if not cu.file_exists(BINARY):
            logger.info("%s: building robustirc", node)
            control.exec_(control.lit(
                "env GOPATH=/root/gocode GOBIN=/root/gocode/bin "
                "go install github.com/robustirc/robustirc@latest"))
        # ONE shared cert for the whole cluster, generated once on the
        # control node and uploaded everywhere (robustirc.clj:39-41) —
        # per-node certs would fail inter-node TLS verification since
        # each server's cert must validate against -tls_ca_file
        local_cert, local_key = shared_cert(test)
        control.upload([local_cert], CERT)
        control.upload([local_key], KEY)
        cu.rm_rf(DATA_DIR)
        cu.mkdir(DATA_DIR)
        primary = (test.get("nodes") or [node])[0]
        if node == primary:
            cu.start_daemon(
                {"logfile": f"{DATA_DIR}/robustirc.log",
                 "pidfile": f"{DATA_DIR}/robustirc.pid", "chdir": DATA_DIR},
                BINARY, *base_args(node), "-singlenode")
            cu.await_tcp_port(PORT, host=node, timeout_s=120.0)
        core.synchronize(test, timeout_s=600.0)
        if node != primary:
            cu.start_daemon(
                {"logfile": f"{DATA_DIR}/robustirc.log",
                 "pidfile": f"{DATA_DIR}/robustirc.pid", "chdir": DATA_DIR},
                BINARY, *base_args(node), f"-join={primary}:{PORT}")
            cu.await_tcp_port(PORT, host=node, timeout_s=120.0)
        core.synchronize(test, timeout_s=600.0)

    def teardown(self, test, node):
        self.kill(test, node)
        cu.rm_rf(DATA_DIR)

    def start(self, test, node):
        primary = (test.get("nodes") or [node])[0]
        extra = "-singlenode" if node == primary else f"-join={primary}:{PORT}"
        return cu.start_daemon(
            {"logfile": f"{DATA_DIR}/robustirc.log",
             "pidfile": f"{DATA_DIR}/robustirc.pid", "chdir": DATA_DIR},
            BINARY, *base_args(node), extra)

    def kill(self, test, node):
        cu.grepkill("robustirc")

    def log_files(self, test, node):
        return [f"{DATA_DIR}/robustirc.log"]


class RobustIRCClient(Client):
    """The robustsession set client (robustirc.clj:104-182): adds post
    ``TOPIC #jepsen :<n>``, the whole-set read replays the message log
    and extracts every TOPIC integer."""

    def __init__(self, timeout_s: float = 10.0, node: str | None = None):
        self.timeout_s = timeout_s
        self.node = node
        self.session_id: str | None = None
        self.session_auth: str | None = None
        self._ctx = ssl._create_unverified_context()  # self-signed cert
        self._msg_counter = 0

    def _url(self, path: str) -> str:
        return f"https://{self.node}:{PORT}/robustirc/v1/{path}"

    def _request(self, path: str, body=None, headers=None):
        data = json.dumps(body).encode() if body is not None else None
        hdrs = dict(headers or {})
        if data is not None:
            hdrs["Content-Type"] = "application/json"
        req = urllib.request.Request(
            self._url(path), data=data, headers=hdrs,
            method="POST" if data is not None else "GET")
        with urllib.request.urlopen(req, timeout=self.timeout_s,
                                    context=self._ctx) as resp:
            return json.loads(resp.read().decode() or "null")

    def open(self, test, node):
        c = RobustIRCClient(self.timeout_s, node)
        sess = c._request("session", body={})
        c.session_id = sess["Sessionid"]
        c.session_auth = sess["Sessionauth"]
        c._post(f"NICK j{node}")
        c._post("USER j j j j")
        c._post(f"JOIN {CHANNEL}")
        return c

    def _post(self, irc_line: str):
        """POST one IRC message with a collision-resistant id
        (robustirc.clj:108-121)."""
        self._msg_counter += 1
        digest = hashlib.md5(
            f"{irc_line}-{self._msg_counter}".encode()).hexdigest()
        msg_id = int(digest[:15], 16)
        return self._request(
            f"{self.session_id}/message",
            body={"Data": irc_line, "ClientMessageId": msg_id},
            headers={"X-Session-Auth": self.session_auth})

    def _read_topics(self) -> list[int]:
        """Stream the message log; collect TOPIC integers
        (robustirc.clj:123-148).

        The GetMessages stream never closes — it waits for future
        events — so termination needs a marker: we post a uniquely-
        tagged PRIVMSG first and stream exactly until it comes back,
        which yields a consistent prefix of the log."""
        marker = f"jepsen-read-marker-{uuid.uuid4().hex}"
        self._post(f"PRIVMSG {CHANNEL} :{marker}")
        req = urllib.request.Request(
            self._url(f"{self.session_id}/messages?lastseen=0.0"),
            headers={"X-Session-Auth": self.session_auth})
        out: list[int] = []
        with urllib.request.urlopen(req, timeout=self.timeout_s,
                                    context=self._ctx) as resp:
            decoder = json.JSONDecoder()
            buf = ""
            while True:
                chunk = resp.read(65536).decode(errors="replace")
                if not chunk:
                    break  # server closed early; partial → caller fails op
                buf += chunk
                while buf:
                    buf = buf.lstrip()
                    try:
                        msg, idx = decoder.raw_decode(buf)
                    except json.JSONDecodeError:
                        break
                    buf = buf[idx:]
                    data = (msg or {}).get("Data", "")
                    if marker in data:
                        return out
                    parts = data.split(" ")
                    if len(parts) > 1 and parts[1] == "TOPIC":
                        try:
                            out.append(int(data.split(":")[-1]))
                        except ValueError:
                            pass
        raise ConnectionError("message stream closed before marker")

    def invoke(self, test, op):
        f, v = op.get("f"), op.get("value")
        try:
            if f == "add":
                self._post(f"TOPIC {CHANNEL} :{int(v)}")
                return {**op, "type": "ok"}
            if f == "read":
                try:
                    topics = self._read_topics()
                except NET_ERRORS:
                    # a streaming read cut short mid-log would report a
                    # partial set and yield false 'lost' verdicts
                    return {**op, "type": "fail", "error": ["stream-cut"]}
                return {**op, "type": "ok", "value": sorted(set(topics))}
            return {**op, "type": "fail", "error": ["unknown-f", f]}
        except NET_ERRORS as e:
            kind = "fail" if f == "read" else "info"
            return {**op, "type": kind, "error": ["net", str(e)]}


SUPPORTED_WORKLOADS = ("set",)


def robustirc_test(opts_dict: dict | None = None) -> dict:
    return build_suite_test(
        opts_dict, db_name="robustirc",
        supported_workloads=SUPPORTED_WORKLOADS,
        make_real=lambda o: {"db": RobustIRCDB(),
                             "client": RobustIRCClient(), "os": Debian()})


main = cli.single_test_cmd(
    standard_test_fn(robustirc_test),
    standard_opt_fn(SUPPORTED_WORKLOADS),
    name="jepsen-robustirc")


if __name__ == "__main__":
    import sys
    sys.exit(main())
