"""Operating-system automation (reference: jepsen/src/jepsen/os.clj +
os/debian.clj, os/centos.clj, os/ubuntu.clj, os/smartos.clj).

An OS prepares a node for DB installation: hostnames, base packages,
package-manager plumbing (os.clj:4-8).
"""
from __future__ import annotations

import logging

from jepsen_tpu import control

logger = logging.getLogger("jepsen.os")


class OS:
    def setup(self, test: dict, node: str) -> None:
        pass

    def teardown(self, test: dict, node: str) -> None:
        pass


class Noop(OS):
    """jepsen.os/noop"""


def setup_hostfile(test: dict) -> None:
    """Writes /etc/hosts mapping every node name to its IP
    (os/debian.clj setup-hostfile!)."""
    from jepsen_tpu.net import resolve_ip
    lines = ["127.0.0.1 localhost"]
    for n in test.get("nodes") or []:
        lines.append(f"{resolve_ip(test, n)} {n}")
    content = "\n".join(lines) + "\n"
    with control.su():
        control.exec_("tee", "/etc/hosts", stdin=content)


class Debian(OS):
    """apt-based setup (os/debian.clj)."""

    def __init__(self, extra_packages: list[str] | None = None):
        self.extra_packages = extra_packages or []

    base_packages = [
        "curl", "faketime", "iptables", "iputils-ping", "logrotate",
        "man-db", "net-tools", "ntpdate", "psmisc", "rsyslog", "sudo",
        "tar", "unzip", "wget",
    ]

    def setup(self, test, node):
        def go():
            setup_hostfile(test)
            with control.su():
                maybe_update()
                install(self.base_packages + self.extra_packages)
        control.on(node, test, go)

    def teardown(self, test, node):
        pass


class Ubuntu(Debian):
    """os/ubuntu.clj — identical surface, different base packages."""

    base_packages = [p for p in Debian.base_packages if p != "faketime"]


class CentOS(OS):
    """yum-based setup (os/centos.clj)."""

    def setup(self, test, node):
        def go():
            setup_hostfile(test)
            with control.su():
                control.exec_("yum", "-y", "install", "sudo", "curl", "wget",
                              "unzip", "tar", "iptables", "psmisc")
        control.on(node, test, go)


class SmartOS(OS):
    """pkgin-based setup (os/smartos.clj)."""

    def setup(self, test, node):
        def go():
            with control.su():
                control.exec_("pkgin", "-y", "update")
                control.exec_("pkgin", "-y", "install", "curl", "gnu-coreutils")
        control.on(node, test, go)


# --- apt helpers (os/debian.clj:39+) --------------------------------------

def maybe_update(max_age_s: int = 86400) -> None:
    """apt-get update unless the cache is fresh (os/debian.clj:39-44)."""
    r = control.exec_star(
        "sh", "-c",
        f"test -z \"$(find /var/cache/apt -maxdepth 0 -mmin -{max_age_s // 60})\" "
        f"&& apt-get update || true")
    _ = r


def installed(packages) -> set:
    """Subset of packages already installed (os/debian.clj:45+)."""
    if isinstance(packages, str):
        packages = [packages]
    out = control.exec_star("dpkg-query", "-W", "-f", "${Package}\\n", *packages)
    return set(out.out.split()) & set(packages)


def install(packages) -> None:
    if isinstance(packages, str):
        packages = [packages]
    missing = [p for p in packages if p not in installed(packages)]
    if missing:
        control.exec_("env", "DEBIAN_FRONTEND=noninteractive", "apt-get",
                      "install", "-y", *missing)


def installed_version(package: str) -> str | None:
    r = control.exec_star("dpkg-query", "-W", "-f", "${Version}", package)
    return r.out.strip() if r.exit_status == 0 and r.out.strip() else None


def add_repo(name: str, line: str, keyserver: str | None = None,
             key_id: str | None = None) -> None:
    """Adds an apt source list plus (optionally) its signing key
    (os/debian.clj add-repo!, used by galera.clj:37-41 and
    percona.clj:37-42)."""
    control.exec_("sh", "-c",
                  f"echo {control.escape(line)} > "
                  f"/etc/apt/sources.list.d/{name}.list")
    if keyserver and key_id:
        control.exec_("apt-key", "adv", "--keyserver", keyserver,
                      "--recv-keys", key_id)
    control.exec_("apt-get", "update")


def debconf_set(selection: str) -> None:
    """Pre-seeds a debconf answer (the reference's
    ``echo ... | debconf-set-selections`` pattern, galera.clj:44-46)."""
    control.exec_("debconf-set-selections", stdin=selection + "\n")


debian = Debian
centos = CentOS
ubuntu = Ubuntu
smartos = SmartOS
noop = Noop
