"""Operating-system automation (reference: jepsen/src/jepsen/os.clj +
os/debian.clj, os/centos.clj, os/ubuntu.clj, os/smartos.clj).

An OS prepares a node for DB installation: hostnames, base packages,
package-manager plumbing (os.clj:4-8).
"""
from __future__ import annotations

import logging

from jepsen_tpu import control

logger = logging.getLogger("jepsen.os")


class OS:
    def setup(self, test: dict, node: str) -> None:
        pass

    def teardown(self, test: dict, node: str) -> None:
        pass


class Noop(OS):
    """jepsen.os/noop"""


def setup_hostfile(test: dict) -> None:
    """Writes /etc/hosts mapping every node name to its IP
    (os/debian.clj setup-hostfile!)."""
    from jepsen_tpu.net import resolve_ip
    lines = ["127.0.0.1 localhost"]
    for n in test.get("nodes") or []:
        lines.append(f"{resolve_ip(test, n)} {n}")
    content = "\n".join(lines) + "\n"
    with control.su():
        control.exec_("tee", "/etc/hosts", stdin=content)


class Debian(OS):
    """apt-based setup (os/debian.clj)."""

    def __init__(self, extra_packages: list[str] | None = None):
        self.extra_packages = extra_packages or []

    base_packages = [
        "curl", "faketime", "iptables", "iputils-ping", "logrotate",
        "man-db", "net-tools", "ntpdate", "psmisc", "rsyslog", "sudo",
        "tar", "unzip", "wget",
    ]

    def setup(self, test, node):
        def go():
            setup_hostfile(test)
            with control.su():
                maybe_update()
                install(self.base_packages + self.extra_packages)
        control.on(node, test, go)

    def teardown(self, test, node):
        pass


class Ubuntu(Debian):
    """os/ubuntu.clj — identical surface, different base packages."""

    base_packages = [p for p in Debian.base_packages if p != "faketime"]


class CentOS(OS):
    """yum/rpm-based setup (os/centos.clj).

    Beyond the package list, the reference's CentOS does three RH-specific
    things this mirrors: it patches the 127.0.0.1 line of /etc/hosts to
    include the node's own hostname (centos.clj:12-25 — RH images often
    miss it and Java networking breaks), it installs the C toolchain
    (gcc/gcc-c++) that the clock nemesis needs for its on-node builds
    (nemesis/time.py compiles bump-time.c with the node's gcc; ref
    nemesis/time.clj:52-61), and it bootstraps ``start-stop-daemon`` —
    absent on RH — from the dpkg source tarball (centos.clj:110-121)
    because the shared daemon helpers depend on it.
    """

    base_packages = [
        "wget", "gcc", "gcc-c++", "curl", "vim-common", "unzip", "rsyslog",
        "iptables", "ncurses-devel", "iproute", "logrotate", "sudo", "tar",
        "psmisc",
    ]

    def __init__(self, extra_packages: list[str] | None = None):
        self.extra_packages = extra_packages or []

    def setup(self, test, node):
        def go():
            setup_hostfile(test)
            patch_loopback_hostname()
            with control.su():
                yum_maybe_update()
                yum_install(self.base_packages + self.extra_packages)
                install_start_stop_daemon()
            net = test.get("net")
            if net is not None:
                try:
                    net.heal(test)  # meh'd like the reference (u/meh)
                except Exception:  # noqa: BLE001
                    logger.exception("net heal during OS setup failed")
        control.on(node, test, go)

    def teardown(self, test, node):
        pass


class SmartOS(OS):
    """pkgin-based setup (os/smartos.clj, the full surface): loopback
    hostfile patch, age-gated ``pkgin update`` (judged by
    /var/db/pkgin/sql.log's mtime like the reference's
    time-since-last-update), installed-set-aware package install, the
    ipfilter service enabled via ``svcadm``, and a best-effort net heal.
    Commands run under su — illumos roles would use pfexec, but the
    reference drives SmartOS through the same c/su wrapper this mirrors.
    """

    base_packages = ["wget", "curl", "vim", "unzip", "rsyslog", "logrotate"]

    def __init__(self, extra_packages: list[str] | None = None):
        self.extra_packages = extra_packages or []

    def setup(self, test, node):
        def go():
            setup_hostfile(test)
            patch_loopback_hostname()
            with control.su():
                pkgin_maybe_update()
                pkgin_install(self.base_packages + self.extra_packages)
                control.exec_("svcadm", "enable", "-r", "ipfilter")
            net = test.get("net")
            if net is not None:
                try:
                    net.heal(test)  # meh'd like the reference (u/meh)
                except Exception:  # noqa: BLE001
                    logger.exception("net heal during OS setup failed")
        control.on(node, test, go)


# --- apt helpers (os/debian.clj:39+) --------------------------------------

def maybe_update(max_age_s: int = 86400) -> None:
    """apt-get update unless the cache is fresh (os/debian.clj:39-44)."""
    r = control.exec_star(
        "sh", "-c",
        f"test -z \"$(find /var/cache/apt -maxdepth 0 -mmin -{max_age_s // 60})\" "
        f"&& apt-get update || true")
    _ = r


def installed(packages) -> set:
    """Subset of packages already installed (os/debian.clj:45+)."""
    if isinstance(packages, str):
        packages = [packages]
    out = control.exec_star("dpkg-query", "-W", "-f", "${Package}\\n", *packages)
    return set(out.out.split()) & set(packages)


def install(packages) -> None:
    if isinstance(packages, str):
        packages = [packages]
    missing = [p for p in packages if p not in installed(packages)]
    if missing:
        control.exec_("env", "DEBIAN_FRONTEND=noninteractive", "apt-get",
                      "install", "-y", *missing)


def installed_version(package: str) -> str | None:
    r = control.exec_star("dpkg-query", "-W", "-f", "${Version}", package)
    return r.out.strip() if r.exit_status == 0 and r.out.strip() else None


def add_repo(name: str, line: str, keyserver: str | None = None,
             key_id: str | None = None) -> None:
    """Adds an apt source list plus (optionally) its signing key
    (os/debian.clj add-repo!, used by galera.clj:37-41 and
    percona.clj:37-42)."""
    control.exec_("sh", "-c",
                  f"echo {control.escape(line)} > "
                  f"/etc/apt/sources.list.d/{name}.list")
    if keyserver and key_id:
        control.exec_("apt-key", "adv", "--keyserver", keyserver,
                      "--recv-keys", key_id)
    control.exec_("apt-get", "update")


def debconf_set(selection: str) -> None:
    """Pre-seeds a debconf answer (the reference's
    ``echo ... | debconf-set-selections`` pattern, galera.clj:44-46)."""
    control.exec_("debconf-set-selections", stdin=selection + "\n")


# --- yum/rpm helpers (os/centos.clj:28-121) -------------------------------

def patch_loopback_hostname() -> None:
    """Appends the node's hostname to the 127.0.0.1 line of /etc/hosts if
    missing (centos.clj setup-hostfile!)."""
    name = control.exec_("hostname")
    hosts = control.exec_("cat", "/etc/hosts")
    changed = False
    lines = []
    for line in hosts.splitlines():
        if line.startswith("127.0.0.1") and name not in line.split():
            line = f"{line} {name}"
            changed = True
        lines.append(line)
    if changed:
        with control.su():
            control.exec_("tee", "/etc/hosts", stdin="\n".join(lines) + "\n")


def yum_maybe_update(max_age_s: int = 86400) -> None:
    """yum update unless one ran in the last day, judged by the yum log's
    mtime — missing log counts as stale (centos.clj:27-44)."""
    control.exec_(
        "sh", "-c",
        f"test $(( $(date +%s) - "
        f"$(stat -c %Y /var/log/yum.log 2>/dev/null || echo 0) )) "
        f"-lt {max_age_s} || yum -y update")


def pkgin_maybe_update(max_age_s: int = 86400) -> None:
    """pkgin update unless one ran in the last day, judged by pkgin's
    sql.log mtime — missing log counts as stale (smartos.clj:27-43
    time-since-last-update / maybe-update!)."""
    control.exec_(
        "sh", "-c",
        f"test $(( $(date +%s) - "
        f"$(stat -c %Y /var/db/pkgin/sql.log 2>/dev/null || echo 0) )) "
        f"-lt {max_age_s} || pkgin update")


def _pkgin_list() -> list[tuple[str, str]]:
    """[(name, version)] from ``pkgin -p list`` lines of the form
    ``name-version;...`` (smartos.clj:45-57 parse)."""
    import re
    r = control.exec_star("pkgin", "-p", "list")
    out = []
    for line in (r.out or "").splitlines():
        head = line.split(";", 1)[0].strip()
        m = re.match(r"(.+)-([^-]+)$", head)
        if m:
            out.append((m.group(1), m.group(2)))
    return out


def pkgin_installed(packages) -> set:
    """Subset of packages already installed (smartos.clj installed)."""
    names = {n for n, _ in _pkgin_list()}
    return {p for p in packages if p in names}


def pkgin_installed_version(pkg: str) -> str | None:
    """Installed version of a pkgin package, or None
    (smartos.clj:70-81)."""
    for n, v in _pkgin_list():
        if n == pkg:
            return v
    return None


def pkgin_install(pkgs) -> None:
    """Ensures packages are present: a flat collection installs any
    missing name, a {pkg: version} map pins versions
    (smartos.clj:83-103)."""
    if isinstance(pkgs, dict):
        listed = dict(_pkgin_list())
        for pkg, version in pkgs.items():
            if listed.get(pkg) != version:
                control.exec_("pkgin", "-y", "install", f"{pkg}-{version}")
        return
    present = pkgin_installed(pkgs)
    missing = [p for p in pkgs if p not in present]
    if missing:
        control.exec_("pkgin", "-y", "install", *missing)


def pkgin_uninstall(pkgs) -> None:
    """Removes whichever of the packages are installed
    (smartos.clj:59-64)."""
    if isinstance(pkgs, str):
        pkgs = [pkgs]
    present = sorted(pkgin_installed(pkgs))
    if present:
        control.exec_("pkgin", "-y", "remove", *present)


def yum_installed(packages) -> set:
    """Subset of packages already installed, via rpm -q (the query side of
    centos.clj installed — rpm answers directly instead of grepping
    ``yum list installed``)."""
    if isinstance(packages, str):
        packages = [packages]
    r = control.exec_star("rpm", "-q", "--qf", "%{NAME}\\n", *packages)
    # rpm prints "package X is not installed" for misses ON STDOUT — only
    # single-token lines are real package names
    names = {line.strip() for line in r.out.splitlines()
             if line.strip() and " " not in line.strip()}
    return names & set(packages)


def yum_installed_version(package: str) -> str | None:
    """Installed version of a package, or None (centos.clj:74-86)."""
    r = control.exec_star("rpm", "-q", "--qf", "%{VERSION}", package)
    return r.out.strip() if r.exit_status == 0 and r.out.strip() else None


def yum_install(packages) -> None:
    """Ensures packages are installed; a dict pins versions
    (centos.clj:88-107)."""
    if isinstance(packages, dict):
        for pkg, version in packages.items():
            if yum_installed_version(pkg) != version:
                control.exec_("yum", "-y", "install", f"{pkg}-{version}")
        return
    if isinstance(packages, str):
        packages = [packages]
    present = yum_installed(packages)
    missing = [p for p in packages if p not in present]
    if missing:
        control.exec_("yum", "-y", "install", *missing)


def yum_uninstall(packages) -> None:
    """Removes the installed subset of packages (centos.clj:59-66)."""
    if isinstance(packages, str):
        packages = [packages]
    installed = yum_installed(packages)
    present = [p for p in packages if p in installed]
    if present:
        control.exec_("yum", "-y", "remove", *present)


_SSD_DPKG_VERSION = "1.17.27"


def install_start_stop_daemon(sha256: str | None = None) -> None:
    """Builds start-stop-daemon from the dpkg source tarball when absent —
    RH systems don't ship it, and the shared daemon helpers
    (control/util.py) drive services through it (centos.clj:110-127).

    The tarball is fetched over HTTPS from the official Debian mirror
    (transport integrity); pass ``sha256`` to additionally pin the
    artifact — deployments that require supply-chain pinning should
    supply the digest of the mirror copy they vetted."""
    if control.exec_star("test", "-x",
                         "/usr/bin/start-stop-daemon").exit_status == 0:
        return
    v = _SSD_DPKG_VERSION
    control.exec_("wget", "-nv",
                  f"https://deb.debian.org/debian/pool/main/d/dpkg/dpkg_{v}.tar.xz")
    if sha256:
        control.exec_("sh", "-c",
                      f"echo '{sha256}  dpkg_{v}.tar.xz' | sha256sum -c -")
    control.exec_("tar", "-xf", f"dpkg_{v}.tar.xz")
    control.exec_("sh", "-c",
                  f"cd dpkg-{v} && ./configure && make -C utils")
    control.exec_("cp", f"dpkg-{v}/utils/start-stop-daemon",
                  "/usr/bin/start-stop-daemon")
    control.exec_("rm", "-rf", f"dpkg_{v}.tar.xz", f"dpkg-{v}")


OS_REGISTRY = {
    "debian": Debian,
    "ubuntu": Ubuntu,
    "centos": CentOS,
    "smartos": SmartOS,
    "noop": Noop,
}


def os_by_name(name: str) -> type[OS]:
    """Maps a CLI ``--os`` choice to its OS class."""
    try:
        return OS_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown os {name!r}; choose from {sorted(OS_REGISTRY)}") from None


debian = Debian
centos = CentOS
ubuntu = Ubuntu
smartos = SmartOS
noop = Noop
