"""Control-node persistent cache for expensive artifacts (DB builds,
downloads), keyed by logical paths.

Reference: jepsen/src/jepsen/fs_cache.clj — strings/EDN/files/remote
files cached under a base dir; atomic rename writes; per-path locks.
Values here are strings/JSON/files; deploy pushes a cached file to the
current remote node.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
import threading
from pathlib import Path
from typing import Any

DEFAULT_DIR = os.path.expanduser("~/.jepsen-tpu/cache")

_locks: dict = {}
_locks_guard = threading.Lock()


def cache_dir() -> Path:
    return Path(os.environ.get("JEPSEN_CACHE_DIR", DEFAULT_DIR))


def _encode_component(c: Any) -> str:
    s = str(c)
    return "".join(ch if ch.isalnum() or ch in "-._" else "_" for ch in s)


def cache_path(path_key) -> Path:
    """Logical key (sequence or scalar) -> filesystem path
    (fs_cache.clj encode)."""
    if not isinstance(path_key, (list, tuple)):
        path_key = [path_key]
    return cache_dir().joinpath(*[_encode_component(c) for c in path_key])


def lock(path_key) -> threading.Lock:
    """A per-key lock (fs_cache.clj locking)."""
    key = str(cache_path(path_key))
    with _locks_guard:
        return _locks.setdefault(key, threading.Lock())


def exists(path_key) -> bool:
    return cache_path(path_key).exists()


def _atomic_write(dest: Path, write_fn) -> None:
    dest.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(dest.parent), prefix=".cache-tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
            f.flush()
            # fsync before the rename: os.replace without it can publish
            # a torn/empty cache entry after a power cut, and a corrupt
            # cache entry silently feeds every later run
            os.fsync(f.fileno())
        os.replace(tmp, dest)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def save_string(path_key, s: str) -> None:
    _atomic_write(cache_path(path_key), lambda f: f.write(s.encode()))


def load_string(path_key) -> str | None:
    p = cache_path(path_key)
    return p.read_text() if p.exists() else None


def save_data(path_key, value: Any) -> None:
    """JSON value (the reference caches EDN; fs_cache.clj save-edn!)."""
    _atomic_write(cache_path(path_key),
                  lambda f: f.write(json.dumps(value).encode()))


def load_data(path_key) -> Any:
    p = cache_path(path_key)
    return json.loads(p.read_text()) if p.exists() else None


def save_file(path_key, local_path) -> Path:
    """Copies a local file into the cache (atomic)."""
    dest = cache_path(path_key)
    with open(local_path, "rb") as src:
        _atomic_write(dest, lambda f: shutil.copyfileobj(src, f))
    return dest


def file_path(path_key) -> Path | None:
    p = cache_path(path_key)
    return p if p.exists() else None


def save_remote_file(path_key, remote_path: str) -> Path:
    """Downloads a file from the current control session's node into the
    cache (fs_cache.clj save-remote-file!)."""
    from jepsen_tpu import control
    dest = cache_path(path_key)
    dest.parent.mkdir(parents=True, exist_ok=True)
    with tempfile.TemporaryDirectory(dir=str(dest.parent)) as td:
        local = Path(td) / "download"
        control.download(remote_path, str(local))
        if local.exists():
            os.replace(local, dest)
    return dest


def deploy_remote_file(path_key, remote_path: str) -> bool:
    """Uploads a cached file to the current session's node; False when the
    key is absent (fs_cache.clj deploy-remote-file!)."""
    from jepsen_tpu import control
    p = file_path(path_key)
    if p is None:
        return False
    control.upload(str(p), remote_path)
    return True


def clear(path_key=None) -> None:
    if path_key is None:
        shutil.rmtree(cache_dir(), ignore_errors=True)
    else:
        p = cache_path(path_key)
        if p.is_dir():
            shutil.rmtree(p, ignore_errors=True)
        elif p.exists():
            p.unlink()
