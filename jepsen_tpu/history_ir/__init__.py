"""One device-resident columnar history IR (ROADMAP item 3).

``history_ir.of(test, history)`` is the whole integration surface for
checkers: it returns the run's shared :class:`DeviceHistory` — building
it on first call (or adopting the WAL-streamed builder when
``ir_stream_from_wal`` ran), memoizing it on the test map under
``_history_ir`` (underscore keys never serialize) — or None when the IR
is disabled (``ir_enabled: False``) or there is no test map to share
through. Every checker then derives its encoding as a memoized view
(:mod:`jepsen_tpu.history_ir.views`), so a multi-checker run encodes
the history exactly once.

Knobs (test map; preflight-validated, tolerantly coerced like every
other bool knob):

* ``ir_enabled`` — default True; False restores the per-checker encode
  paths bit-identically (the views ARE the encoders, so off/on cannot
  diverge — differential tests pin it).
* ``ir_stream_from_wal`` — default False; True makes ``core.run`` tail
  its own WAL into an incremental IR builder during the run, hiding
  encode latency under the run itself.
"""
from __future__ import annotations

import logging
import threading

from jepsen_tpu.history_ir.builder import (
    IncrementalHistoryBuilder, WalStreamer,
)
from jepsen_tpu.history_ir.ir import CANONICAL_COLUMNS, DeviceHistory

logger = logging.getLogger("jepsen.history_ir")

__all__ = [
    "DeviceHistory", "IncrementalHistoryBuilder", "WalStreamer",
    "CANONICAL_COLUMNS", "of", "enabled", "stream_from_wal_enabled",
    "maybe_start_wal_streamer",
]

#: test-map key the shared IR memoizes under (underscore: never serialized)
ATTACH_KEY = "_history_ir"
STREAMER_KEY = "_ir_streamer"

# one lock for the attach-or-build race: Compose checks run checkers
# concurrently and both may ask for the IR in the same tick
_ATTACH_LOCK = threading.Lock()


def enabled(test) -> bool:
    """The ``ir_enabled`` knob, tolerantly coerced (default True)."""
    from jepsen_tpu.parallel import coerce_flag
    if not isinstance(test, dict):
        return True
    flag = coerce_flag(test.get("ir_enabled"), knob="ir_enabled")
    return True if flag is None else flag


def stream_from_wal_enabled(test) -> bool:
    """The ``ir_stream_from_wal`` knob, tolerantly coerced (default
    False — streaming costs a poller thread; runs opt in)."""
    from jepsen_tpu.parallel import coerce_flag
    if not isinstance(test, dict):
        return False
    flag = coerce_flag(test.get("ir_stream_from_wal"),
                       knob="ir_stream_from_wal")
    return False if flag is None else flag


def of(test, history) -> DeviceHistory | None:
    """The run's shared IR for ``history``, or None when disabled or
    there's no test map to memoize on. Reuses the cached IR only when
    it was built for this exact history object (analyze re-indexes the
    history into new dicts; a stale IR must never serve a different
    list). Prefers the WAL-streamed builder's snapshot when one ran and
    its ops verify against this history."""
    if not isinstance(test, dict) or not enabled(test) or history is None:
        return None
    with _ATTACH_LOCK:
        cached = test.get(ATTACH_KEY)
        if isinstance(cached, DeviceHistory) and cached.ops is history:
            return cached
        dh = None
        streamer = test.get(STREAMER_KEY)
        if streamer is not None:
            try:
                dh = streamer.snapshot_for(history)
            except Exception:  # noqa: BLE001 — streamed IR is an optimization
                logger.exception("WAL-streamed IR adoption failed; "
                                 "batch-building")
                dh = None
            if dh is not None:
                logger.info("adopted WAL-streamed history IR (%d ops)",
                            len(dh))
        if dh is None:
            try:
                dh = DeviceHistory.from_ops(history)
            except Exception:  # noqa: BLE001 — the IR is an optimization:
                # a history the column encoder can't pack (non-numeric
                # time, unhashable process — a hand-edited or foreign
                # history.jsonl) must fall back to the per-checker
                # legacy encodes, never fail the check
                logger.warning("history IR build failed; checkers fall "
                               "back to per-checker encodes",
                               exc_info=True)
                return None
        # pin the caller's list itself (from_ops copies it) so the
        # cached-IR identity check above recognizes repeat calls
        dh.ops = history
        test[ATTACH_KEY] = dh
        return dh


def maybe_start_wal_streamer(test, wal_path):
    """Starts the background WAL->IR streamer for a run when
    ``ir_stream_from_wal`` (and the IR itself) is on; returns the
    streamer or None. Installed under ``_ir_streamer`` so
    :func:`of` finds it at analysis time; ``core.run`` drains it before
    discarding the WAL and pops it on teardown."""
    if not (enabled(test) and stream_from_wal_enabled(test)):
        return None
    try:
        streamer = WalStreamer(wal_path).start()
    except Exception:  # noqa: BLE001 — streaming must not fail the run
        logger.exception("couldn't start WAL->IR streamer")
        return None
    test[STREAMER_KEY] = streamer
    return streamer
