"""The device-resident columnar history IR: :class:`DeviceHistory`.

One canonical struct-of-arrays encoding of a run's history, produced
once and consumed zero-copy by every checker backend (ROADMAP item 3;
the Arrow-style one-layout-many-consumers stance). It *promotes*
:class:`jepsen_tpu.history.ColumnarHistory` — same packed int columns
(type/process/f/time/index plus the invocation pairing) — and adds:

* a **value-id column** + value :class:`~jepsen_tpu.history.Intern`
  table, so workload values are dense int32 ids the kernels can consume
  without a per-checker re-interning pass;
* **memoized views** (:meth:`DeviceHistory.view`): each checker derives
  its encoding (register event stream, Elle builder columns, set
  membership matrix, per-key sub-histories — see
  :mod:`jepsen_tpu.history_ir.views`) from the IR exactly once per run;
  a second checker over the same history pays ~nothing
  (``ir_encode_amortization`` in bench.py pins this);
* **device placement** (:meth:`DeviceHistory.device_columns`): the
  canonical columns staged onto the accelerator — single-device or
  padded + sharded over a :func:`jepsen_tpu.parallel.auto_mesh` mesh
  via the per-device transfer lanes — and cached per mesh. The
  checker kernels today consume IR-derived *views* (event streams,
  Elle columns) whose planners stage per device themselves; this is
  the placement surface for consumers that want the raw columns
  device-resident (guarded by the ``no-host-roundtrip`` lint rule).

The builder half (incremental, streamed from the PR-3 WAL) lives in
:mod:`jepsen_tpu.history_ir.builder`; the ``.npz`` sidecar
serialization in :mod:`jepsen_tpu.history_ir.sidecar`.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from jepsen_tpu.history import ColumnarHistory, Intern

#: canonical packed-int column names, in sidecar order
CANONICAL_COLUMNS = ("types", "processes", "fs", "times", "indices",
                     "completion_of", "invocation_of", "value_ids")


class ValueIntern(Intern):
    """Intern specialized for op *values*: unhashable values (lists —
    the universal op-value shape: cas pairs, txn micro-ops) key by a
    repr freeze like the base class, but the TABLE keeps the original
    value, so ``value(id)`` returns what the op actually carried and
    the sidecar's codec round-trip is faithful (the base class stores
    the marker tuple itself, which is fine for f-name interning but
    lossy for values)."""

    def id(self, v) -> int:
        try:
            i = self._ids.get(v)
            key = v
        except TypeError:  # unhashable: freeze the key, keep the value
            key = ("__unhashable__", repr(v))
            i = self._ids.get(key)
        if i is None:
            i = len(self.table)
            self._ids[key] = i
            self.table.append(v)
        return i


@dataclass
class DeviceHistory(ColumnarHistory):
    """ColumnarHistory promoted to the one shared checker IR.

    All base columns keep their dtypes and semantics; ``value_ids``
    interns every op's ``value`` (id 0 = None) into ``intern``. Views
    and device placements are memoized on the instance — build the IR
    once per run (``history_ir.of``) and every checker shares it.
    """

    value_ids: np.ndarray | None = None  # int32 into intern
    intern: Intern = field(default_factory=ValueIntern)
    _views: dict = field(default_factory=dict, repr=False, compare=False)
    _lock: threading.RLock = field(default_factory=threading.RLock,
                                   repr=False, compare=False)

    @classmethod
    def from_ops(cls, history: Sequence[dict],
                 intern: Intern | None = None) -> "DeviceHistory":
        dh = super().from_ops(history)
        dh.intern = intern or ValueIntern()
        vid = dh.intern.id
        dh.value_ids = np.fromiter((vid(v) for v in dh.values),
                                   np.int32, len(dh.values))
        return dh

    # -- memoized views --------------------------------------------------

    def view(self, key, build: Callable):
        """The memoized derived view for ``key`` (any hashable), built
        by ``build()`` exactly once. Concurrent checkers (Compose's
        bounded_pmap) serialize on the first build and then share the
        product — this is the "encode once, every checker a view"
        contract. A ``build`` that raises caches nothing."""
        with self._lock:
            if key not in self._views:
                self._views[key] = build()
            return self._views[key]

    def view_keys(self) -> tuple:
        with self._lock:
            return tuple(self._views)

    # -- device placement ------------------------------------------------

    def device_columns(self, mesh=None) -> tuple[dict, int]:
        """The canonical int columns resident on device, memoized per
        mesh: ``(arrays, n_real)``. With ``mesh=None`` every column is
        staged whole onto the default device; with a mesh the op axis
        is padded to a device multiple and sharded over the per-device
        transfer lanes (:func:`jepsen_tpu.parallel.shard_chunked`), so
        mesh consumers read their shard without a resharding copy.
        Padding rows are all-zero with process/pairing -1 (no checker
        semantics: consumers slice to ``n_real``)."""
        key = ("__device__", None if mesh is None
               else (int(mesh.devices.size), tuple(mesh.axis_names)))
        return self.view(key, lambda: self._place(mesh))

    def _place(self, mesh) -> tuple[dict, int]:
        import jax

        from jepsen_tpu import parallel
        n = len(self)
        cols = {name: getattr(self, name) for name in CANONICAL_COLUMNS}
        if mesh is None:
            return {k: jax.device_put(v) for k, v in cols.items()}, n
        nd = int(mesh.devices.size)
        rem = (-n) % nd
        if rem:
            pad = {"processes": -1, "completion_of": -1,
                   "invocation_of": -1}
            cols = {k: np.concatenate(
                        [v, np.full(rem, pad.get(k, 0), v.dtype)])
                    for k, v in cols.items()}
        placed = parallel.shard_chunked(mesh, list(cols.values()))
        return dict(zip(cols, placed)), n
