"""Host ingest spine: the native WAL tail→parse→IR fast path.

The WAL hot loop — newline scan, JSON parse, canonical-column append,
live register encode, frontier absorb — runs in the C extension
(``native/columnar_ext.c``) when it's available and provably identical,
and in the pure-Python twins otherwise (doc/performance.md "Host ingest
spine"). This module is the dispatch layer:

* **Knob**: the ``ingest_native`` test-map key / ``JEPSEN_TPU_INGEST_NATIVE``
  env twin turn the native path off (it defaults on). Coercion is
  tolerant — "0"/"false"/"off" disable, anything else keeps the default.
* **Probe**: before first use the native entry points run a canned
  differential (torn lines, unicode escapes, surrogates, big ints,
  cas pairs, a frontier death) against the Python twins; any divergence
  disables the native path for the process and bumps the fallback
  counter. The same one-shot latch as the elle columnar parser.
* **Fallback counter**: ``native_ingest_fallback_total{reason}`` in the
  process registry counts every drop back to Python (missing compiler,
  probe mismatch, per-chunk regime bail, frontier death replay), so a
  fleet receiver silently running the slow path shows up in metrics.

Bit-identity contract: every native entry point either mutates the SAME
Python-level state its twin owns (builder columns, encoder dicts) in
the twin's exact order, or works on copies and lets the caller replay
the twin from untouched state. The differential suites in
tests/test_history_ir.py and tests/test_live.py pin both directions.
"""
from __future__ import annotations

import contextlib
import gc
import json
import logging
import os
import threading

logger = logging.getLogger("jepsen.history_ir")

# sentinels for the per-line fallback protocol (see _line_fallback)
_SKIP = object()  # whitespace-only line: skipped, not counted
_TORN = object()  # undecodable line: torn, counted

_TRUTHY = {"1", "true", "yes", "on", "force", "native"}
_FALSY = {"0", "false", "no", "off", "python", "disabled"}


def coerce_flag(value, default: bool = True) -> bool:
    """Tolerant knob coercion: bools pass through, common string forms
    map, anything unrecognized keeps the default (a typo'd knob must
    not silently flip a correctness-adjacent path)."""
    if value is None:
        return default
    if isinstance(value, bool):
        return value
    s = str(value).strip().lower()
    if s in _TRUTHY:
        return True
    if s in _FALSY:
        return False
    return default


_lock = threading.Lock()
# set while the probe differential runs: the probe drives the Python
# twins, which re-enter native_mod() — the flag makes those nested
# calls take the pure path instead of deadlocking on _lock
_tls = threading.local()
# probe state: None = not probed yet; True/False = probe verdict latch
_probe_ok: bool | None = None
# test-map override recorded by configure_from_test (env still wins
# when the test map is silent)
_test_override: bool | None = None
# sanitizer-variant request (test map ``native_san`` / env twin
# JEPSEN_TPU_NATIVE_SAN). Defaults OFF: the ASan build is a slow-lane
# correctness tool, never the production spine.
_test_override_san: bool | None = None


@contextlib.contextmanager
def ingest_burst():
    """Defers the cyclic GC for the duration of one drain/consume burst.

    The spine allocates container objects (op dicts, value lists,
    column ints) at millions per second; letting the generational
    collector run between chunk calls walks the whole accumulated
    session state every few hundred thousand ops and costs a large
    fraction of ingest throughput. Collection is deferred, never
    skipped — the enclosing loop re-enables GC between bursts, so a
    burst is bounded garbage (one poll's worth). Nested/disabled states
    pass through untouched."""
    if not gc.isenabled():
        yield
        return
    gc.disable()
    try:
        yield
    finally:
        gc.enable()


def fallback_count(reason: str, n: int = 1) -> None:
    """Bumps ``native_ingest_fallback_total{reason}``."""
    try:
        from jepsen_tpu import telemetry
        telemetry.get_registry().counter(
            "native_ingest_fallback_total",
            "ingest work that fell back to the Python path",
            labels=("reason",)).inc(n, reason=reason)
    except Exception:  # noqa: BLE001 — metrics never break ingest
        pass


def configure_from_test(test: dict | None) -> None:
    """Records the test map's ``ingest_native`` knob so consumers that
    never see the test map (tailers, sessions) honor it. Env twin
    ``JEPSEN_TPU_INGEST_NATIVE`` still applies when the map is silent."""
    global _test_override
    if test is None:
        return
    v = test.get("ingest_native")
    _test_override = None if v is None else coerce_flag(v, default=True)
    global _test_override_san
    s = test.get("native_san")
    _test_override_san = (None if s is None
                          else coerce_flag(s, default=False))


def reset() -> None:
    """Test hook: forget the probe latch and test-map overrides."""
    global _probe_ok, _test_override, _test_override_san
    with _lock:
        _probe_ok = None
        _test_override = None
        _test_override_san = None


def _knob_on() -> bool:
    if _test_override is not None:
        return _test_override
    return coerce_flag(os.environ.get("JEPSEN_TPU_INGEST_NATIVE"),
                       default=True)


def san_on() -> bool:
    """True when the sanitizer variant of the native spine is requested
    (test map ``native_san``, env twin ``JEPSEN_TPU_NATIVE_SAN``)."""
    if _test_override_san is not None:
        return _test_override_san
    return coerce_flag(os.environ.get("JEPSEN_TPU_NATIVE_SAN"),
                       default=False)


def _mod():
    """The C module with the spine entry points, or None. When the
    sanitizer lane is requested, ONLY the ASan+UBSan build qualifies —
    an uninstrumented module must never masquerade as the san lane, so
    unavailability means the Python twins, loudly counted."""
    from jepsen_tpu.native import columnar_c
    m = columnar_c.mod(san=san_on())
    if m is None or not hasattr(m, "ingest_chunk"):
        return None  # no compiler, build failed, or a stale .so
    return m


def native_mod():
    """The probed-and-trusted native module, or None (Python twins).

    First call runs the differential probe; the verdict latches for the
    process (the existing probe/disable protocol of the columnar
    parser, extended with a self-check)."""
    global _probe_ok
    if not _knob_on():
        return None
    if _probe_ok is False:
        return None
    if getattr(_tls, "probing", False):
        return None  # twins run pure-Python inside the differential
    m = _mod()
    if m is None:
        if _probe_ok is None:
            with _lock:
                if _probe_ok is None:
                    _probe_ok = False
            if san_on():
                # distinct reason: a requested-but-missing sanitizer
                # build must never be confused with a plain build miss
                fallback_count("san-unavailable")
                logger.warning(
                    "sanitizer ingest build requested "
                    "(native_san/JEPSEN_TPU_NATIVE_SAN) but unavailable "
                    "in this process; using Python ingest twins")
            else:
                fallback_count("build")
                logger.info("native ingest unavailable (no compiled "
                            "extension); using Python ingest twins")
        return None
    if _probe_ok:
        return m
    # the probe runs OUTSIDE _lock: it drives the Python twins, which
    # re-enter this function (the _tls.probing flag routes them pure),
    # and holding a non-reentrant lock across that re-entry is a
    # self-deadlock shape. Two threads racing here at most probe twice
    # — the differential is pure (fresh builders, canned bytes), so
    # the duplicate is harmless and the verdict latch below is
    # first-writer-wins.
    _tls.probing = True
    try:
        verdict = _probe(m)
    finally:
        _tls.probing = False
    with _lock:
        if _probe_ok is None:
            _probe_ok = verdict
            if not verdict:
                fallback_count("probe")
    return m if _probe_ok else None


def enabled() -> bool:
    return native_mod() is not None


def sim_lane():
    """``columnar_c.sim_lane`` when the native plane is enabled and
    probed, else None (the simulated scheduler runs its pure loop).
    generator/simulate.py resolves this per simulate() call, so the
    knob/probe latch governs the scheduler lane exactly like the WAL
    spine entry points."""
    m = native_mod()
    return getattr(m, "sim_lane", None) if m is not None else None


# -- per-line fallback (shared with the C scanner) ----------------------

def _line_fallback(line: bytes):
    """Decides parse/skip/torn for a line the C parser bailed on, with
    WalTailer.poll's tolerant semantics: decode with replacement, skip
    whitespace-only lines silently, count undecodable lines torn."""
    s = line.decode("utf-8", "replace")
    if not s or s.isspace():
        return _SKIP
    try:
        return json.loads(s)
    except json.JSONDecodeError:
        return _TORN


def parse_wal_chunk(chunk: bytes, final: bool = False):
    """``(ops, consumed, torn, truncated)`` for a raw WAL byte chunk —
    native scan+parse when trusted, else the Python twin in journal.py.
    ``consumed`` covers exactly the newline-terminated prefix (plus the
    dropped tail when ``final``), so the caller's offset/prefix-sha
    cursor advances identically on both paths."""
    m = native_mod()
    if m is not None:
        ops, consumed, torn, truncated = m.ingest_chunk(
            chunk, final, _line_fallback, _SKIP, _TORN)
        return ops, consumed, torn, bool(truncated)
    from jepsen_tpu.journal import parse_wal_chunk_py
    return parse_wal_chunk_py(chunk, final=final)


# -- builder / encoder / frontier adapters ------------------------------

def _builder_state(b):
    return (b.ops, b._types, b._procs, b._fs, b._times, b._indices,
            b._value_ids, b.values, b._completion_of, b._invocation_of,
            b._open_invoke, b._f_intern._ids, b._f_intern.table,
            b._v_intern._ids, b._v_intern.table, b.add)


def builder_extend(builder, ops: list, start: int = 0) -> bool:
    """Appends ``ops[start:]`` into the builder's canonical columns on
    the native path; returns False when the caller must run the Python
    twin instead (builder shape outside the fast regime)."""
    m = native_mod()
    if m is None or not isinstance(ops, list):
        return False
    from jepsen_tpu.history import Intern
    from jepsen_tpu.history_ir.ir import ValueIntern
    if (type(builder._f_intern) is not Intern
            or type(builder._v_intern) is not ValueIntern):
        fallback_count("regime")
        return False
    m.builder_extend(ops, start, _builder_state(builder))
    builder._snapshot = None
    return True


def _encoder_eligible(enc) -> bool:
    from jepsen_tpu.history import Intern
    return bool(enc._default_args) and type(enc.intern) is Intern


def encoder_add(enc, ops: list, start: int = 0) -> bool:
    """LiveRegisterEncoder.add over a chunk, natively. False = caller
    runs the per-op Python twin."""
    m = native_mod()
    if m is None or not isinstance(ops, list) or not _encoder_eligible(enc):
        return False
    m.register_add(ops, start, (enc._ops, enc._open_inv, enc._outcome,
                                enc.add))
    return True


def encoder_add_encode(enc, ops: list, start: int = 0) -> bool:
    """Fused LiveRegisterEncoder.add_many + encode_resolved: the
    chunk's op dicts are classified once in C, with the add pass's
    field reads feeding the encoder directly. Encoding eagerly here is
    observationally identical — encode_resolved is a deterministic
    cursor advance over ``_ops``, so running it at add time instead of
    at the next verdict lands in the same state. False = caller runs
    the per-op Python add twin (and encoding stays lazy)."""
    m = native_mod()
    if (m is None or not isinstance(ops, list)
            or not _encoder_eligible(enc)):
        return False
    s = enc.stream
    nxt, next_slot, n_slots, enc_ran, bailed = m.register_add_encode(
        ops, start,
        (enc._ops, enc._open_inv, enc._outcome, enc.add),
        (enc._ops, enc._outcome, enc._open_by_process, enc._free_slots,
         s.kind, s.slot, s.f, s.a, s.b, s.op_index,
         enc.intern._ids, enc.intern.table,
         enc._next, enc._next_slot, s.n_slots, enc._finalized))
    if enc_ran:
        enc._next, enc._next_slot, s.n_slots = nxt, next_slot, n_slots
        if bailed:
            # cursor is AT the offending op; the next encode_resolved
            # resumes (and raises) through the Python twin from there
            fallback_count("encode-bail")
    return True


def encoder_encode(enc) -> bool:
    """LiveRegisterEncoder.encode_resolved, natively. Advances the
    encoder's cursor/slots in place; a mid-stream bail leaves the
    cursor AT the offending op so the Python twin resumes (and raises)
    from bit-identical state. False = caller runs the twin outright."""
    m = native_mod()
    if m is None or not _encoder_eligible(enc):
        return False
    s = enc.stream
    nxt, next_slot, n_slots, bailed = m.register_encode(
        (enc._ops, enc._outcome, enc._open_by_process, enc._free_slots,
         s.kind, s.slot, s.f, s.a, s.b, s.op_index,
         enc.intern._ids, enc.intern.table,
         enc._next, enc._next_slot, s.n_slots, enc._finalized))
    enc._next, enc._next_slot, s.n_slots = nxt, next_slot, n_slots
    if bailed:
        fallback_count("encode-bail")
        return False  # twin resumes from enc._next
    return True


def frontier_absorb(fs, stream, start: int, end: int | None = None):
    """FrontierSession.absorb on the native path. Returns True when the
    session state advanced natively; False when the caller must run
    the Python twin (regime miss, config blow-up, or frontier death —
    the C works on copies, so the twin replays from untouched state
    and produces the identical failure forensics)."""
    m = native_mod()
    if m is None or fs.failure is not None:
        return False
    from jepsen_tpu.checker.linear_cpu import cas_register_step_py
    if fs.step is not cas_register_step_py:
        return False
    kind = stream.kind
    if not isinstance(kind, list):
        return False  # numpy-backed streams take the Python loop
    if end is None:
        end = len(kind)
    out = m.frontier_absorb(fs.configs, fs.cur, fs.cur_idx,
                            fs.pending_mask, kind, stream.slot, stream.f,
                            stream.a, stream.b, stream.op_index,
                            start, end, fs.configs_max)
    if out is None:
        fallback_count("frontier-bail")
        return False
    if len(out) == 2 and out[0] == "dead":
        fallback_count("frontier-dead")
        return False  # twin replays for the failure payload
    configs, cur, cur_idx, pending, cmax, _seen = out
    fs.configs = configs
    fs.cur = cur
    fs.cur_idx = cur_idx
    fs.pending_mask = pending
    fs.configs_max = cmax
    fs.events_absorbed = end
    return True


# -- the probe -----------------------------------------------------------

_PROBE_WAL = (
    b'{"type":"invoke","f":"write","value":3,"process":0,"time":11}\n'
    b'{"type":"ok","f":"write","value":3,"process":0,"time":12}\n'
    b'{"type":"invoke","f":"cas","value":[3,1],"process":1,"time":13}\n'
    b'\n'
    b'{"torn": tr\n'
    b'{"type":"ok","f":"cas","value":[3,1],"process":1,"time":14}\n'
    b'{"u":"\\ud83d\\ude00 caf\\u00e9 \\ud800","big":123456789012345678901,'
    b'"neg":-0,"x":1.5e-3,"inf":Infinity}\n'
    b'{"type":"invoke","f":"read","value":null,"process":2,"time":15}\n'
    b'{"type":"ok","f":"read","value":1,"process":2,"time":16}\n'
    b'{"type":"invoke","f":"read","value":null,"process":0,"time":17'
)  # unterminated tail


def _deep_eq(a, b) -> bool:
    if type(a) is not type(b):
        return False
    if isinstance(a, dict):
        return (set(a) == set(b)
                and all(_deep_eq(a[k], b[k]) for k in a))
    if isinstance(a, (list, tuple)):
        return (len(a) == len(b)
                and all(_deep_eq(x, y) for x, y in zip(a, b)))
    if isinstance(a, float):
        return repr(a) == repr(b)  # -0.0, nan-payload exactness
    return a == b


def _probe(m) -> bool:
    """One-shot differential of every native entry point against its
    Python twin over a canned nasty WAL. Any divergence (or exception)
    condemns the native path for the process."""
    try:
        from jepsen_tpu.checker.linear_cpu import FrontierSession
        from jepsen_tpu.checker.linear_encode import (
            EV_INVOKE, EV_NOOP, EV_RETURN,
        )
        from jepsen_tpu.history import Intern
        from jepsen_tpu.history_ir.builder import (
            IncrementalHistoryBuilder, LiveRegisterEncoder,
        )
        from jepsen_tpu.journal import parse_wal_chunk_py
        from jepsen_tpu.models import CAS_F_CAS, CAS_F_READ, CAS_F_WRITE
        if (EV_INVOKE, EV_RETURN, EV_NOOP) != (0, 1, 2):
            return False  # C hardcodes these
        if (CAS_F_READ, CAS_F_WRITE, CAS_F_CAS) != (0, 1, 2):
            return False
        for final in (False, True):
            got = m.ingest_chunk(_PROBE_WAL, final, _line_fallback,
                                 _SKIP, _TORN)
            want = parse_wal_chunk_py(_PROBE_WAL, final=final)
            if not (_deep_eq(list(got[0]), list(want[0]))
                    and got[1] == want[1] and got[2] == want[2]
                    and bool(got[3]) == bool(want[3])):
                logger.warning("native ingest probe: chunk parse "
                               "diverged (final=%s); disabling", final)
                return False
        ops = parse_wal_chunk_py(_PROBE_WAL, final=True)[0]
        ops = [o for o in ops if isinstance(o, dict) and "type" in o]
        b1, b2 = IncrementalHistoryBuilder(), IncrementalHistoryBuilder()
        for o in ops:
            b1.add(o)
        m.builder_extend(ops, 0, _builder_state(b2))
        for at in ("ops", "_types", "_procs", "_fs", "_times", "_indices",
                   "_value_ids", "values", "_completion_of",
                   "_invocation_of", "_open_invoke"):
            if not _deep_eq(getattr(b1, at), getattr(b2, at)):
                logger.warning("native ingest probe: builder column %s "
                               "diverged; disabling", at)
                return False
        if (b1._f_intern.table != b2._f_intern.table
                or b1._v_intern.table != b2._v_intern.table):
            logger.warning("native ingest probe: intern tables "
                           "diverged; disabling")
            return False
        e1 = LiveRegisterEncoder(Intern())
        e2 = LiveRegisterEncoder(Intern())
        for o in ops:
            e1.add(o)
        m.register_add(ops, 0, (e2._ops, e2._open_inv, e2._outcome,
                                e2.add))
        e1._finalized = e2._finalized = True
        e1.encode_resolved()
        s2 = e2.stream
        nxt, nslot, nslots, bailed = m.register_encode(
            (e2._ops, e2._outcome, e2._open_by_process, e2._free_slots,
             s2.kind, s2.slot, s2.f, s2.a, s2.b, s2.op_index,
             e2.intern._ids, e2.intern.table,
             e2._next, e2._next_slot, s2.n_slots, e2._finalized))
        e2._next, e2._next_slot, s2.n_slots = nxt, nslot, nslots
        if bailed:
            e2.encode_resolved()
        s1 = e1.stream
        for at in ("kind", "slot", "f", "a", "b", "op_index", "n_slots"):
            if getattr(s1, at) != getattr(s2, at):
                logger.warning("native ingest probe: encoder stream %s "
                               "diverged; disabling", at)
                return False
        if (e1._next, e1._next_slot, e1._free_slots, e1._open_by_process) \
                != (e2._next, e2._next_slot, e2._free_slots,
                    e2._open_by_process):
            logger.warning("native ingest probe: encoder cursor "
                           "diverged; disabling")
            return False
        f1, f2 = FrontierSession(), FrontierSession()
        f1.absorb(s1, 0, len(s1.kind))
        out = m.frontier_absorb(f2.configs, f2.cur, f2.cur_idx,
                                f2.pending_mask, s2.kind, s2.slot, s2.f,
                                s2.a, s2.b, s2.op_index, 0, len(s2.kind),
                                f2.configs_max)
        if out is None or (len(out) == 2 and out[0] == "dead"):
            f2.absorb(s2, 0, len(s2.kind))
        else:
            (f2.configs, f2.cur, f2.cur_idx, f2.pending_mask,
             f2.configs_max) = out[:5]
            f2.events_absorbed = len(s2.kind)
        if (f1.configs != f2.configs or f1.cur != f2.cur
                or f1.cur_idx != f2.cur_idx
                or f1.pending_mask != f2.pending_mask
                or f1.configs_max != f2.configs_max
                or f1.failure != f2.failure):
            logger.warning("native ingest probe: frontier state "
                           "diverged; disabling")
            return False
        if hasattr(m, "sim_lane"):
            from jepsen_tpu.generator.simulate import _lane_probe
            if not _lane_probe(m.sim_lane):
                logger.warning("native ingest probe: scheduler lane "
                               "diverged; disabling")
                return False
        return True
    except Exception:  # noqa: BLE001 — a crashing probe condemns native
        logger.exception("native ingest probe crashed; disabling")
        return False
