"""Checker views over the history IR: encode once, consume everywhere.

Every checker backend's encoding is a *view* derived from one
:class:`~jepsen_tpu.history_ir.ir.DeviceHistory`, memoized on the IR
instance (``dh.view``), so a multi-checker run (Compose, the analyze
re-check, the bench lanes) pays each encode exactly once:

* :func:`register_stream` / :func:`multi_register_stream` — the
  linearizability :class:`~jepsen_tpu.checker.linear_encode.EventStream`
  (``checker.linear_encode`` delegates its module functions here; the
  encoder bodies now live in ONE place).
* :func:`elle_build` / :func:`elle_columns` — the Elle list-append
  builder product (``elle.columnar``'s graph parts and storable
  columns).
* :func:`txn_nodes` — the ok/fail/info node split every elle-style
  checker (list-append Python path, rw-register) starts from.
* :func:`set_full_columns` — the set-full membership matrix the
  setscan kernel consumes (moved out of ``checker.SetFullChecker``).
* :func:`subhistories` — the per-key split ``independent`` checkers
  fan out over.

Device placement of the canonical columns is
:meth:`DeviceHistory.device_columns` (mesh-aware); view products that
feed kernels (event streams, matrix chunks) are staged by the kernels'
own planners, which already pool/pad per device. Functions here must
not round-trip device arrays back to host — the ``no-host-roundtrip``
lint rule enforces that on checker-path code.
"""
from __future__ import annotations

import numpy as np

from jepsen_tpu.checker.linear_encode import EV_INVOKE, EV_RETURN
from jepsen_tpu.history import Intern
from jepsen_tpu.history_ir.ir import DeviceHistory


def _key_of(v) -> str:
    """A stable hashable memo-key fragment for an arbitrary value."""
    try:
        hash(v)
        return v
    except TypeError:
        return repr(v)


# ---------------------------------------------------------------------------
# register event stream (linearizability)
# ---------------------------------------------------------------------------


def encode_register_ops(history, intern: Intern | None = None,
                        encode_args=None):
    """Encodes a single-register r/w/cas history (the reference
    tutorial's etcd workload; BASELINE configs 1-3) into an
    EventStream. THE implementation — ``checker.linear_encode
    .encode_register_ops`` is a thin delegate, and the memoized
    :func:`register_stream` view wraps it for IR consumers.

    Op encodings (f, a, b):
      read v  -> (CAS_F_READ, id(v), 0); a read of None (id 0) matches any state
      write v -> (CAS_F_WRITE, id(v), 0)
      cas [u,v] -> (CAS_F_CAS, id(u), id(v))

    ``encode_args(op) -> (f, a, b)`` overrides the per-op encoding (the
    invoke/completion pairing, slot assignment, and crashed-read
    handling are model-independent — encode_multi_register_ops reuses
    them)."""
    from jepsen_tpu.checker.linear_encode import EventStream
    from jepsen_tpu.models import CAS_F_CAS, CAS_F_READ, CAS_F_WRITE
    if isinstance(history, DeviceHistory):
        history = history.ops
    intern = intern or Intern()
    kinds, slots, fs, as_, bs, idxs = [], [], [], [], [], []
    open_by_process: dict = {}   # process -> (slot, op)
    free_slots: list[int] = []
    next_slot = 0
    n_ops = 0

    if encode_args is None:
        def encode_args(op):
            f, v = op.get("f"), op.get("value")
            if f == "read":
                return CAS_F_READ, intern.id(v), 0
            if f == "write":
                return CAS_F_WRITE, intern.id(v), 0
            if f == "cas":
                u, w = v
                return CAS_F_CAS, intern.id(u), intern.id(w)
            raise ValueError(f"unknown register op {f!r}")

    # First pass: pair invokes with completions; find fail pairs and crashed
    # reads to drop; *complete* invocation values from their returns
    # (knossos history/complete semantics — a read's definitive value
    # arrives with its :ok, but the search consumes it at the invoke event).
    drop = set()
    open_inv: dict = {}
    completed_value: dict[int, object] = {}  # invoke idx -> definitive value
    for i, op in enumerate(history):
        p, typ = op.get("process"), op.get("type")
        if not isinstance(p, int) or p < 0:
            drop.add(i)
            continue
        if typ == "invoke":
            open_inv[p] = i
        elif typ == "fail":
            j = open_inv.pop(p, None)
            if j is not None:
                drop.add(j)
            drop.add(i)
        elif typ == "ok":
            j = open_inv.pop(p, None)
            if j is not None and op.get("value") is not None:
                completed_value[j] = op.get("value")
        elif typ == "info":
            j = open_inv.pop(p, None)
            drop.add(i)  # info completion itself is not an event
            if j is not None and history[j].get("f") == "read":
                drop.add(j)  # crashed reads have no effect
    # ops still open at the end of history (no completion at all) crash too
    for p, j in open_inv.items():
        if history[j].get("f") == "read":
            drop.add(j)

    for i, op in enumerate(history):
        if i in drop:
            continue
        p, typ = op.get("process"), op.get("type")
        if typ == "invoke":
            if free_slots:
                s = free_slots.pop()
            else:
                s = next_slot
                next_slot += 1
            open_by_process[p] = (s, i)
            inv = dict(op)
            if i in completed_value:
                inv["value"] = completed_value[i]
            fcode, a, b = encode_args(inv)
            kinds.append(EV_INVOKE)
            slots.append(s)
            fs.append(fcode)
            as_.append(a)
            bs.append(b)
            idxs.append(i)
            n_ops += 1
        elif typ == "ok":
            got = open_by_process.pop(p, None)
            if got is None:
                continue
            s, j = got
            kinds.append(EV_RETURN)
            slots.append(s)
            fs.append(0)
            as_.append(0)
            bs.append(0)
            idxs.append(i)
            free_slots.append(s)
        # info: no return event — the crashed op's slot stays occupied
        # forever, so it may be linearized at any later point or never.

    return EventStream(
        kind=np.array(kinds, dtype=np.int8),
        slot=np.array(slots, dtype=np.int32),
        f=np.array(fs, dtype=np.int32),
        a=np.array(as_, dtype=np.int32),
        b=np.array(bs, dtype=np.int32),
        op_index=np.array(idxs, dtype=np.int32),
        n_slots=max(next_slot, 1),
        n_ops=n_ops,
        intern=intern,
    )


class _DenseIntern:
    """Stands in for Intern when states are arithmetic encodings rather
    than interned values: only the state-count surface is needed."""

    def __init__(self, n: int):
        self._n = n

    def __len__(self):
        return self._n


def encode_multi_register_ops(history, n_keys: int = 3, n_values: int = 5):
    """Encodes a multi-register txn history (the multi-key-acid workload,
    yugabyte/multi_key_acid.clj) for models.multi_register_spec: one op
    f="txn" whose value is [[f, k, v], ...] packs into base-(2V+2)
    per-key action digits of ``a`` (see the spec for the layout).

    The packed encoding holds one action per key, which covers the
    workload's generators exactly (they draw random nonempty *subsets*
    of the key range, so a txn never touches a key twice); a history
    with repeated keys in one txn raises ValueError and the checker
    falls back to the object-model search."""
    V, K = n_values, n_keys
    AB = 2 * V + 2

    def encode_args(op):
        if op.get("f") != "txn":
            raise ValueError(f"multi-register op must be txn, got "
                             f"{op.get('f')!r}")
        acts = [0] * K
        for f, k, v in op.get("value") or ():
            if not isinstance(k, int) or not (0 <= k < K):
                raise ValueError(f"key {k!r} outside [0, {K})")
            if acts[k] != 0:
                raise ValueError(f"txn touches key {k} twice")
            if f == "r":
                if v is None:
                    acts[k] = 1
                elif isinstance(v, int) and 0 <= v < V:
                    acts[k] = 2 + v
                else:
                    raise ValueError(f"read value {v!r} outside [0, {V})")
            elif f == "w":
                if not (isinstance(v, int) and 0 <= v < V):
                    raise ValueError(f"write value {v!r} outside [0, {V})")
                acts[k] = 2 + V + v
            else:
                raise ValueError(f"unknown micro-op {f!r}")
        a = 0
        for k in reversed(range(K)):
            a = a * AB + acts[k]
        return 0, a, 0

    stream = encode_register_ops(history, encode_args=encode_args)
    # interned-state count for kernel selection: the whole map space
    stream.intern = _DenseIntern((V + 1) ** K)
    return stream


def register_stream(dh: DeviceHistory, init_value=None):
    """The memoized register EventStream view. ``init_value`` (the
    model's initial register value) interns FIRST so its id is the
    kernel's init state — the memo is keyed on it."""
    def build():
        intern = Intern()
        if init_value is not None:
            intern.id(init_value)
        return encode_register_ops(dh.ops, intern=intern)
    return dh.view(("register-stream", _key_of(init_value)), build)


def multi_register_stream(dh: DeviceHistory, n_keys: int, n_values: int):
    """The memoized multi-register EventStream view, or None when the
    history falls outside the packed encoding (checker wgl-falls-back)."""
    def build():
        try:
            return encode_multi_register_ops(dh.ops, n_keys, n_values)
        except ValueError:
            return None
    return dh.view(("multi-register-stream", n_keys, n_values), build)


# ---------------------------------------------------------------------------
# elle (list-append) views
# ---------------------------------------------------------------------------


def elle_build(dh: DeviceHistory):
    """The memoized Elle dependency-graph build product
    ((graph, txns, extras, n_keys) — ``elle.columnar._build``), or None
    when the history is outside the integer columnar regime."""
    def build():
        from jepsen_tpu.elle import columnar
        try:
            return columnar._build(dh.ops)
        except (TypeError, ValueError, OverflowError):
            return None
    return dh.view(("elle-build",), build)


def elle_columns(dh: DeviceHistory):
    """The memoized storable Elle builder columns
    (``elle.columnar.parse_columns``), or None when not storable."""
    def build():
        from jepsen_tpu.elle import columnar
        return columnar.parse_columns(dh.ops)
    return dh.view(("elle-columns",), build)


def txn_nodes(dh: DeviceHistory) -> tuple[list, list, list]:
    """The memoized (oks, fails, infos) op split every elle-style
    checker starts from (list-append's Python builder, rw-register)."""
    def build():
        oks = [op for op in dh.ops if op.get("type") == "ok"
               and isinstance(op.get("process"), int)]
        fails = [op for op in dh.ops if op.get("type") == "fail"]
        infos = [op for op in dh.ops if op.get("type") == "info"
                 and isinstance(op.get("process"), int)]
        return oks, fails, infos
    return dh.view(("txn-nodes",), build)


# ---------------------------------------------------------------------------
# set-full membership columns (checker.SetFullChecker's device path)
# ---------------------------------------------------------------------------


def set_full_columns(history) -> dict:
    """The set-full checker's device encoding: every element's
    add-invoke/add-ok times plus the reads x elements membership matrix
    the setscan kernel classifies. Moved here from
    ``checker.SetFullChecker._check_device`` so the encode is an IR
    view (memoized per run) instead of a per-checker pass.

    Returns ``{"member", "read_t", "invoke_t", "ok_t", "has_ok",
    "els"}`` — or ``{"error": ...}`` when the set was never read."""
    from jepsen_tpu.history import Intern as _Intern
    if isinstance(history, DeviceHistory):
        history = history.ops

    intern = _Intern()
    invoke_t: list[float] = []
    ok_t: list[float] = []
    has_ok: list[bool] = []
    has_invoke: list[bool] = []

    def el_slot(v):
        i = intern.id(v) - 1  # id 0 is the None sentinel
        while len(invoke_t) <= i:
            invoke_t.append(0.0)
            ok_t.append(0.0)
            has_ok.append(False)
            has_invoke.append(False)
        return i

    reads: list[tuple[float, object]] = []  # (invoke time, raw payload)
    pending_read_invokes: dict = {}

    # -- adds: vectorized first-invoke / last-ok per element --------
    # the per-event Python walk dominated the host side of this
    # checker at bench scale; for the universal all-int regime the
    # same semantics (invoke_t = first add event's time, ok_t =
    # last ok's — el_slot's exact behavior) fall out of masked
    # first/last-occurrence joins. Non-int elements keep the loop.
    nh = len(history)
    # cheap gate first: the columnar path serves only all-int add
    # values, and a non-int history must not pay for mask building
    fast = any(op.get("f") == "add" for op in history) and \
        all(type(op.get("value")) is int for op in history
            if op.get("f") == "add")
    scan = range(nh)
    if fast:
        fs = [op.get("f") for op in history]
        typs = [op.get("type") for op in history]
        add_m = np.fromiter((f == "add" for f in fs), bool, nh)
        inv_m = np.fromiter((t == "invoke" for t in typs), bool, nh)
        ok_m = np.fromiter((t == "ok" for t in typs), bool, nh)
        add_pos = np.nonzero(add_m & (inv_m | ok_m))[0]
        fast = add_pos.size > 0
    if fast:
        add_idx = add_pos.tolist()
        t_add = np.fromiter(
            (float(history[i].get("time", i)) for i in add_idx),
            np.float64, add_pos.size)
        va = np.asarray([history[i].get("value") for i in add_idx],
                        np.int64)
        uniq, first_idx, inverse = np.unique(
            va, return_index=True, return_inverse=True)
        order = np.argsort(first_idx)
        rank = np.empty(order.size, np.int64)
        rank[order] = np.arange(order.size)
        el_ids = rank[inverse]
        for v in uniq[order].tolist():
            intern.id(v)   # same table the read fallback consults
        E_fast = int(uniq.size)
        _, first_per_el = np.unique(el_ids, return_index=True)
        ok_arr = np.zeros(E_fast)
        has_ok_arr = np.zeros(E_fast, bool)
        ok_sel = np.nonzero(ok_m[add_pos])[0]
        if ok_sel.size:
            el_ok = el_ids[ok_sel][::-1]
            t_ok = t_add[ok_sel][::-1]
            u_ok, last_rev = np.unique(el_ok, return_index=True)
            ok_arr[u_ok] = t_ok[last_rev]
            has_ok_arr[u_ok] = True
        invoke_t = t_add[first_per_el].tolist()
        ok_t = ok_arr.tolist()
        has_ok = has_ok_arr.tolist()
        has_invoke = [True] * E_fast
        # only the (few) read events still walk in Python
        read_m = np.fromiter((f == "read" for f in fs), bool, nh)
        scan = np.nonzero(read_m & (inv_m | ok_m))[0].tolist()
    for i in scan:
        op = history[i]
        f, typ, v, p = (op.get("f"), op.get("type"), op.get("value"),
                        op.get("process"))
        if f == "add":
            t = float(op.get("time", i))
            j = el_slot(v)
            if typ == "invoke" and not has_invoke[j]:
                invoke_t[j] = t
                has_invoke[j] = True
            elif typ == "ok":
                ok_t[j] = t
                has_ok[j] = True
                if not has_invoke[j]:  # ok with no invoke (CPU parity)
                    invoke_t[j] = t
                    has_invoke[j] = True
        elif f == "read":
            t = float(op.get("time", i))
            if typ == "invoke":
                pending_read_invokes[p] = t
            elif typ == "ok":
                t0 = pending_read_invokes.pop(p, t)
                reads.append((t0, v))
    if not reads:
        return {"error": "Set was never read"}
    E = len(invoke_t)
    reads.sort(key=lambda rv: rv[0])
    member = np.zeros((len(reads), max(E, 1)), dtype=bool)
    # Columnar fast path for the common set workload (integer
    # elements): map each read payload to element columns with one
    # sorted-array searchsorted instead of a per-element dict walk —
    # the membership matrix build is the device path's host-side cost
    # and must not dominate the kernel it feeds. Elements a read
    # mentions that were never added are ignored on both paths.
    uv_sorted = uv_order = None
    vals = intern.table[1:E + 1]
    if E and all(type(x) is int for x in vals):
        uv = np.asarray(vals, np.int64)
        uv_order = np.argsort(uv)
        uv_sorted = uv[uv_order]
    for r, (_, vs) in enumerate(reads):
        if uv_sorted is not None:
            try:
                arr = np.asarray(vs if type(vs) is list else list(vs))
            except (TypeError, ValueError, OverflowError):
                arr = None
            # signed-int dtype only: asarray would silently coerce
            # floats ('2.5' -> 2) or parse digit strings, making a
            # read "contain" elements it never mentioned
            if arr is not None and arr.ndim == 1 \
                    and arr.dtype.kind == "i":
                arr = arr.astype(np.int64)
                pos = np.clip(np.searchsorted(uv_sorted, arr), 0, E - 1)
                hit = uv_sorted[pos] == arr
                member[r, uv_order[pos[hit]]] = True
                continue
        for v in set(vs):
            j = intern.id(v) - 1
            if 0 <= j < E:
                member[r, j] = True
    return {
        "member": member[:, :max(E, 1)],
        "read_t": np.array([t for t, _ in reads], dtype=np.float32),
        "invoke_t": np.array(invoke_t, dtype=np.float32),
        "ok_t": np.array(ok_t, dtype=np.float32),
        "has_ok": np.array(has_ok, dtype=bool),
        "els": [intern.value(j + 1) for j in range(E)],
    }


def set_membership(dh: DeviceHistory) -> dict:
    """The memoized set-full membership view."""
    return dh.view(("set-full",), lambda: set_full_columns(dh.ops))


# ---------------------------------------------------------------------------
# independent (key-lifted) views
# ---------------------------------------------------------------------------


def subhistories(dh: DeviceHistory) -> tuple[list, dict]:
    """The memoized ``(keys, {frozen_key: sub_history})`` split the
    independent checker fans out over — computed once per run even when
    several composed checkers lift the same history."""
    def build():
        from jepsen_tpu import independent
        keys = independent.history_keys(dh.ops)
        subs = {independent._freeze_key(k):
                independent.subhistory(k, dh.ops) for k in keys}
        return keys, subs
    return dh.view(("subhistories",), build)
