"""Incremental builders for the history IR: encode while the run runs.

The batch path (:meth:`DeviceHistory.from_ops`) walks the finished
history once. The classes here do the same work *op by op* as ops
arrive, so the encode cost hides under the run itself:

* :class:`IncrementalHistoryBuilder` — the canonical-column builder:
  absorbs ops (directly, or tailed from the PR-3 WAL via
  :class:`jepsen_tpu.journal.WalTailer`) and snapshots a
  :class:`~jepsen_tpu.history_ir.ir.DeviceHistory` whose columns are
  bit-identical to the batch build (pinned by tests/test_history_ir.py,
  including torn-WAL resume).
* :class:`WalStreamer` — a background thread ``core.run`` starts when
  the ``ir_stream_from_wal`` knob is on: tails the run's WAL into an
  IncrementalHistoryBuilder so ``history_ir.of`` finds a ready-made IR
  at analysis time instead of paying a post-hoc encode.
* :class:`LiveRegisterEncoder` / :class:`LiveElleColumns` — the per-op
  encode state the live checker sessions (jepsen_tpu.live.sessions)
  adapt over; moved here so the streaming sessions are thin views over
  the IR's builders rather than a parallel encoder lineage.
"""
from __future__ import annotations

import logging
import threading
from typing import Sequence

import numpy as np

from jepsen_tpu.history import Intern, TYPE_CODE
from jepsen_tpu.history_ir.ir import DeviceHistory, ValueIntern

logger = logging.getLogger("jepsen.history_ir")


class IncrementalHistoryBuilder:
    """Builds the canonical IR columns one op at a time.

    ``add`` runs the per-op work (type coding, f/value interning,
    invocation pairing) exactly once; ``snapshot`` converts the
    accumulated lists to a :class:`DeviceHistory` (cached until new ops
    arrive). ``absorb_wal`` pulls whatever a WalTailer has since the
    last poll."""

    def __init__(self):
        self.ops: list[dict] = []
        self._types: list[int] = []
        self._procs: list[int] = []
        self._fs: list[int] = []
        self._times: list[int] = []
        self._indices: list[int] = []
        self._value_ids: list[int] = []
        self.values: list = []
        self._f_intern = Intern()
        self._v_intern = ValueIntern()
        self._completion_of: list[int] = []
        self._invocation_of: list[int] = []
        self._open_invoke: dict = {}
        self._snapshot: DeviceHistory | None = None

    def __len__(self) -> int:
        return len(self.ops)

    def add(self, op: dict) -> None:
        i = len(self.ops)
        self.ops.append(op)
        self._types.append(TYPE_CODE.get(op.get("type"), 3))
        p = op.get("process")
        self._procs.append(p if isinstance(p, int) else -1)
        self._fs.append(self._f_intern.id(op.get("f")))
        self._times.append(op.get("time", 0) or 0)
        idx = op.get("index")
        self._indices.append(i if idx is None else idx)
        v = op.get("value")
        self.values.append(v)
        self._value_ids.append(self._v_intern.id(v))
        # invocation pairing, the pair_index walk online
        self._completion_of.append(-1)
        self._invocation_of.append(-1)
        if op.get("type") == "invoke":
            self._open_invoke[p] = i
        else:
            j = self._open_invoke.pop(p, None)
            if j is not None:
                self._completion_of[j] = i
                self._invocation_of[i] = j
        self._snapshot = None

    def extend(self, ops: Sequence[dict]) -> int:
        # chunked native column append (doc/performance.md "Host ingest
        # spine"): the C twin runs add()'s exact mutation sequence over
        # the whole batch, bailing per-op to self.add for anything
        # outside the fast regime; Python loop when native is off
        from jepsen_tpu.history_ir import ingest
        if ingest.builder_extend(self, ops):
            return len(ops)
        for op in ops:
            self.add(op)
        return len(ops)

    def absorb_wal(self, tailer, final: bool = False) -> int:
        """Absorbs the ops a WalTailer has accumulated since its last
        poll. Torn mid-file lines are skipped by the tailer (counted in
        ``tailer.torn_skipped``); the builder just sees fewer ops and
        the final length check in :meth:`WalStreamer.snapshot_for`
        falls back to a batch build."""
        return self.extend(tailer.poll(final=final))

    def snapshot(self) -> DeviceHistory:
        """The accumulated ops as a DeviceHistory; columns are
        bit-identical to ``DeviceHistory.from_ops(self.ops)``."""
        if self._snapshot is None:
            self._snapshot = DeviceHistory(
                types=np.asarray(self._types, np.int8),
                processes=np.asarray(self._procs, np.int32),
                fs=np.asarray(self._fs, np.int32),
                times=np.asarray(self._times, np.int64),
                indices=np.asarray(self._indices, np.int32),
                completion_of=np.asarray(self._completion_of, np.int32),
                invocation_of=np.asarray(self._invocation_of, np.int32),
                f_table=list(self._f_intern.table),
                values=list(self.values),
                ops=list(self.ops),
                value_ids=np.asarray(self._value_ids, np.int32),
                intern=self._v_intern,
            )
        return self._snapshot


class WalStreamer:
    """Tails a run's WAL into an IncrementalHistoryBuilder on a
    background thread, so the IR is (mostly) built by the time the
    checkers want it. Wedge-proof by construction: the thread is a
    daemon, only touches the local WAL file, and ``drain_final`` joins
    it with a bounded timeout — a hung read abandons streaming and the
    IR falls back to the batch build, never wedging teardown."""

    def __init__(self, wal_path, poll_interval_s: float = 0.25):
        from jepsen_tpu.journal import WalTailer
        self.builder = IncrementalHistoryBuilder()
        self.tailer = WalTailer(wal_path)
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._broken = False
        self._thread = threading.Thread(target=self._run,
                                        name="ir-wal-streamer", daemon=True)

    def start(self) -> "WalStreamer":
        self._thread.start()
        return self

    def _run(self) -> None:  # owner: worker
        while not self._stop.is_set():
            try:
                with self._lock:
                    self.builder.absorb_wal(self.tailer)
            except Exception:  # noqa: BLE001 — streaming is an optimization
                logger.exception("WAL streamer poll failed; stopping")
                self._broken = True
                return
            self._stop.wait(self.poll_interval_s)

    def drain_final(self, timeout_s: float = 5.0) -> None:
        """Stops the poller and absorbs the WAL's final tail. Called
        before the journal is discarded (core.run) so the last ops are
        still on disk when the drain reads them."""
        self._stop.set()
        self._thread.join(timeout=timeout_s)
        if self._thread.is_alive():
            logger.warning("WAL streamer didn't stop in %.1fs; "
                           "abandoning streamed IR", timeout_s)
            self._broken = True
            return
        if self._broken:
            return
        try:
            with self._lock:
                self.builder.absorb_wal(self.tailer, final=True)
        except Exception:  # noqa: BLE001 — fall back to the batch build
            logger.exception("WAL streamer final drain failed")
            self._broken = True

    def snapshot_for(self, history: list[dict]) -> DeviceHistory | None:
        """The streamed IR if it exactly covers ``history``, else None
        (caller batch-builds). The WAL round-trips ops through JSON, so
        every op is compared field-by-field against the in-memory
        history — any divergence (unserializable op dropped, torn line
        skipped, tuple-vs-list value) rejects the stream rather than
        risking a checker seeing different data."""
        if self._broken or self._thread.is_alive():
            return None
        with self._lock:
            ops = self.builder.ops
            if len(ops) != len(history):
                return None
            try:
                for a, b in zip(ops, history):
                    if (a.get("type") != b.get("type")
                            or a.get("process") != b.get("process")
                            or a.get("f") != b.get("f")
                            or (a.get("time", 0) or 0) != (b.get("time", 0) or 0)
                            or a.get("value") != b.get("value")):
                        return None
            except Exception:  # noqa: BLE001 — exotic values: batch build
                return None
            snap = self.builder.snapshot()
        # a FRESH DeviceHistory sharing the (immutable) columns but not
        # the view memo: save-time and analyze-time adoptions see
        # different op dict identities (analyze re-indexes), and views
        # must cite the REAL op dicts of the history they serve
        return DeviceHistory(
            types=snap.types, processes=snap.processes, fs=snap.fs,
            times=snap.times, indices=snap.indices,
            completion_of=snap.completion_of,
            invocation_of=snap.invocation_of,
            f_table=snap.f_table,
            values=[op.get("value") for op in history],
            ops=list(history),
            value_ids=snap.value_ids, intern=snap.intern)


# ---------------------------------------------------------------------------
# live-session encoders (the streaming sessions adapt over these)
# ---------------------------------------------------------------------------


class ListStream:
    """A growing, list-backed event stream the FrontierSession can
    absorb from directly (plain-int lists index faster than numpy
    scalars on the Python step loop) and that converts to a real
    EventStream for device dispatch on demand."""

    __slots__ = ("kind", "slot", "f", "a", "b", "op_index", "intern",
                 "n_slots")

    def __init__(self, intern: Intern):
        self.kind: list[int] = []
        self.slot: list[int] = []
        self.f: list[int] = []
        self.a: list[int] = []
        self.b: list[int] = []
        self.op_index: list[int] = []
        self.intern = intern
        self.n_slots = 1

    def __len__(self):
        return len(self.kind)

    def to_event_stream(self):
        from jepsen_tpu.checker.linear_encode import EV_INVOKE, EventStream
        return EventStream(
            kind=np.asarray(self.kind, np.int8),
            slot=np.asarray(self.slot, np.int32),
            f=np.asarray(self.f, np.int32),
            a=np.asarray(self.a, np.int32),
            b=np.asarray(self.b, np.int32),
            op_index=np.asarray(self.op_index, np.int32),
            n_slots=self.n_slots,
            n_ops=sum(1 for k in self.kind if k == EV_INVOKE),
            intern=self.intern,
        )


class LiveRegisterEncoder:
    """Incremental twin of the register event-stream view
    (:func:`jepsen_tpu.history_ir.views.encode_register_ops`): absorbs
    history ops in order and emits the identical event sequence (pinned
    by a differential fuzz in tests/test_live.py).

    The batch encoder resolves each invoke by looking ahead at its
    completion (fail pairs drop, crashed reads drop, a read's value
    completes from its :ok). Online, the look-ahead becomes a stall:
    encoding advances through the history strictly in order and pauses
    at the first invoke whose completion hasn't arrived yet — the
    *checkable prefix*. The stall is bounded by the run's concurrency
    (plus the per-op deadline that reaps hung ops to :info), and it is
    exactly the live checker's intrinsic lag."""

    def __init__(self, intern: Intern, encode_args=None):
        self.intern = intern
        self.stream = ListStream(intern)
        # snapshot() can only rebuild the default arg encoder; a custom
        # one makes the encoder unsnapshotable (restarts re-ingest)
        self._default_args = encode_args is None
        if encode_args is None:
            from jepsen_tpu.models import (
                CAS_F_CAS, CAS_F_READ, CAS_F_WRITE,
            )

            def encode_args(op):
                f, v = op.get("f"), op.get("value")
                if f == "read":
                    return CAS_F_READ, intern.id(v), 0
                if f == "write":
                    return CAS_F_WRITE, intern.id(v), 0
                if f == "cas":
                    u, w = v
                    return CAS_F_CAS, intern.id(u), intern.id(w)
                raise ValueError(f"unknown register op {f!r}")
        self.encode_args = encode_args
        self._ops: list[dict] = []          # raw history, arrival order
        self._next = 0                      # next history index to encode
        self._open_inv: dict = {}           # process -> open invoke index
        self._outcome: dict[int, tuple] = {}  # invoke idx -> resolution
        # second-pass state (slot allocation), advanced in order only
        self._open_by_process: dict = {}
        self._free_slots: list[int] = []
        self._next_slot = 0
        self._finalized = False

    # -- arrival (first-pass resolution) --------------------------------

    def add(self, op: dict) -> None:
        i = len(self._ops)
        self._ops.append(op)
        p, typ = op.get("process"), op.get("type")
        if not isinstance(p, int) or p < 0:
            return
        if typ == "invoke":
            j = self._open_inv.pop(p, None)
            if j is not None:
                # overwritten invoke: never completed, never dropped by
                # the batch encoder either — encode it, return-less
                self._outcome[j] = ("keep",)
            self._open_inv[p] = i
        elif typ == "fail":
            j = self._open_inv.pop(p, None)
            if j is not None:
                self._outcome[j] = ("drop",)
        elif typ == "ok":
            j = self._open_inv.pop(p, None)
            if j is not None:
                v = op.get("value")
                self._outcome[j] = (("ok", v) if v is not None
                                    else ("keep",))
        elif typ == "info":
            j = self._open_inv.pop(p, None)
            if j is not None:
                self._outcome[j] = (
                    ("drop",) if self._ops[j].get("f") == "read"
                    else ("keep",))

    def add_many(self, ops: Sequence[dict]) -> None:
        """Chunked :meth:`add` — one native call per WAL poll instead
        of a Python frame per op (doc/performance.md "Host ingest
        spine"); falls back to the per-op loop bit-identically."""
        from jepsen_tpu.history_ir import ingest
        if isinstance(ops, list) and ingest.encoder_add_encode(self, ops):
            return
        if ingest.encoder_add(self, ops):
            return
        for op in ops:
            self.add(op)

    # -- encoding (second pass, in order, stalls at unresolved) ---------

    def encode_resolved(self) -> int:
        """Advances the encoder over every op whose resolution is known;
        returns the new count of encoded history ops (the checkable
        prefix length)."""
        # native fast path: advances the same cursor/slot state in
        # place; a mid-stream bail (exotic value, unknown f) leaves
        # ``_next`` AT the offending op so the loop below resumes — and
        # raises — from bit-identical state
        from jepsen_tpu.history_ir import ingest
        if ingest.encoder_encode(self):
            return self._next
        from jepsen_tpu.checker.linear_encode import EV_INVOKE, EV_RETURN
        ops = self._ops
        st = self.stream
        # hot loop: bound methods/locals hoisted — this runs once per
        # history op at WAL-ingest rate
        kind_app, slot_app = st.kind.append, st.slot.append
        f_app, a_app, b_app = st.f.append, st.a.append, st.b.append
        idx_app = st.op_index.append
        outcome_get = self._outcome.get
        free_slots = self._free_slots
        open_bp = self._open_by_process
        encode_args = self.encode_args
        n = len(ops)
        i = self._next
        while i < n:
            op = ops[i]
            p = op.get("process")
            typ = op.get("type")
            if not isinstance(p, int) or p < 0:
                i += 1
                continue
            if typ == "invoke":
                outcome = outcome_get(i)
                if outcome is None:
                    if not self._finalized:
                        break  # stall: completion not seen yet
                    # end of run: open reads never happened, open
                    # mutations stay pending forever (batch semantics)
                    outcome = (("drop",) if op.get("f") == "read"
                               else ("keep",))
                if outcome[0] == "drop":
                    i += 1
                    continue
                if free_slots:
                    s = free_slots.pop()
                else:
                    s = self._next_slot
                    self._next_slot += 1
                    st.n_slots = max(st.n_slots, self._next_slot)
                open_bp[p] = s
                inv = op
                if outcome[0] == "ok":
                    inv = dict(op)
                    inv["value"] = outcome[1]
                fcode, a, b = encode_args(inv)
                kind_app(EV_INVOKE)
                slot_app(s)
                f_app(fcode)
                a_app(a)
                b_app(b)
                idx_app(i)
            elif typ == "ok":
                s = open_bp.pop(p, None)
                if s is not None:
                    kind_app(EV_RETURN)
                    slot_app(s)
                    f_app(0)
                    a_app(0)
                    b_app(0)
                    idx_app(i)
                    free_slots.append(s)
            # fail/info: dropped pair / no return event — the crashed
            # op's slot stays occupied forever
            i += 1
        self._next = i
        return i

    def finalize(self) -> int:
        self._finalized = True
        return self.encode_resolved()

    @property
    def ops_seen(self) -> int:
        return len(self._ops)

    @property
    def ops_encoded(self) -> int:
        return self._next

    # -- durable snapshots (the live daemon's restart path ------------
    #    doc/robustness.md "Resumable checks and the elastic mesh")

    _SCALARS = (type(None), bool, int, float, str)

    # encoded streams longer than this are not snapshotted: the raw-op
    # tail stays tiny (bounded by concurrency), but the encoded int
    # columns grow with the run, and re-serializing tens of MB of JSON
    # every snapshot interval would cost more than the restart re-ingest
    # it avoids. Beyond the cap a daemon restart re-reads the WAL — a
    # bounded few seconds of parse, paid once, instead of a recurring
    # per-poll tax.
    SNAPSHOT_MAX_EVENTS = 1 << 20

    def snapshot(self) -> dict | None:
        """The encoder's resumable state as a JSON-serializable dict,
        or None when it can't be serialized faithfully (exotic intern
        values, a custom ``encode_args``) or economically (the encoded
        columns are past :data:`SNAPSHOT_MAX_EVENTS`). History ops
        before the encode cursor are never consulted again — of the
        RAW history only the unresolved tail is kept (bounded by the
        run's concurrency) — but the encoded columns themselves ride
        along whole, which is what the size cap bounds."""
        if not getattr(self, "_default_args", False):
            return None  # custom encode_args: can't rebuild it
        if len(self.stream) > self.SNAPSHOT_MAX_EVENTS:
            return None  # re-ingest on restart beats a per-poll tax
        if any(not isinstance(v, self._SCALARS)
               for v in self.intern.table):
            return None
        nxt = self._next
        try:
            snap = {
                "intern": list(self.intern.table[1:]),
                "stream": {
                    "kind": list(self.stream.kind),
                    "slot": list(self.stream.slot),
                    "f": list(self.stream.f),
                    "a": list(self.stream.a),
                    "b": list(self.stream.b),
                    "op_index": list(self.stream.op_index),
                    "n_slots": self.stream.n_slots,
                },
                "next": nxt,
                "tail_ops": self._ops[nxt:],
                "open_inv": {str(p): i for p, i in self._open_inv.items()},
                "outcome": {str(i): list(o)
                            for i, o in self._outcome.items() if i >= nxt},
                "open_by_process": {str(p): s for p, s
                                    in self._open_by_process.items()},
                "free_slots": list(self._free_slots),
                "next_slot": self._next_slot,
                "finalized": self._finalized,
            }
            # prove JSON faithfulness now — a tail op with a tuple value
            # or non-string keys must reject here, not diverge later
            import json
            if json.loads(json.dumps(snap)) != snap:
                return None
            return snap
        except (TypeError, ValueError):
            return None

    @classmethod
    def restore(cls, snap: dict) -> "LiveRegisterEncoder | None":
        """An encoder rebuilt from :meth:`snapshot`'s product, or None
        on a malformed snapshot (the caller re-ingests from scratch —
        a bad snapshot may cost a re-read, never a wrong stream)."""
        try:
            intern = Intern()
            for v in snap["intern"]:
                intern.id(v)
            enc = cls(intern)
            st = enc.stream
            s = snap["stream"]
            st.kind = [int(x) for x in s["kind"]]
            st.slot = [int(x) for x in s["slot"]]
            st.f = [int(x) for x in s["f"]]
            st.a = [int(x) for x in s["a"]]
            st.b = [int(x) for x in s["b"]]
            st.op_index = [int(x) for x in s["op_index"]]
            st.n_slots = int(s["n_slots"])
            nxt = int(snap["next"])
            # ops before the cursor are never consulted again —
            # placeholders keep the indexing aligned without the bulk
            enc._ops = [None] * nxt + list(snap["tail_ops"])
            enc._next = nxt
            enc._open_inv = {int(p): int(i)
                             for p, i in (snap.get("open_inv")
                                          or {}).items()}
            enc._outcome = {int(i): tuple(o)
                            for i, o in (snap.get("outcome")
                                         or {}).items()}
            enc._open_by_process = {int(p): int(s2) for p, s2
                                    in (snap.get("open_by_process")
                                        or {}).items()}
            enc._free_slots = [int(x) for x in snap.get("free_slots") or []]
            enc._next_slot = int(snap["next_slot"])
            enc._finalized = bool(snap.get("finalized", False))
            return enc
        except (KeyError, TypeError, ValueError):
            return None


class TxnCols:
    """Flattened micro-op columns for one node class (ok or info)."""

    __slots__ = ("pos", "inv", "proc", "txns",
                 "a_txn", "a_kid", "a_val", "a_mi",
                 "r_txn", "r_kid", "r_mi", "payloads")

    def __init__(self):
        self.pos: list[int] = []
        self.inv: list[int] = []
        self.proc: list[int] = []
        self.txns: list[dict] = []
        self.a_txn: list[int] = []
        self.a_kid: list[int] = []
        self.a_val: list[int] = []
        self.a_mi: list[int] = []
        self.r_txn: list[int] = []
        self.r_kid: list[int] = []
        self.r_mi: list[int] = []
        self.payloads: list[list] = []


class LiveElleColumns:
    """Incremental list-append builder columns: the per-op build work
    (event pairing, micro-op flattening, key interning) run once per op
    as a run's WAL streams in. The live :class:`ElleSession` is a thin
    adapter over this; each verdict pays only the vectorized assemble.
    A history outside the integer columnar regime sets ``fallback`` and
    the session re-checks from the retained history instead."""

    def __init__(self):
        from jepsen_tpu.elle.columnar import _MAX_MOPS, _MAX_VAL
        self._max_mops = _MAX_MOPS
        self._max_val = _MAX_VAL
        self._last_ev: dict = {}      # process -> (idx, was_invoke)
        self.ok = TxnCols()
        self.info = TxnCols()
        self.f_kid: list[int] = []
        self.f_val: list[int] = []
        self._kid_of: dict = {}
        self.raw_key: list = []
        self.fallback: str | None = None

    def kid(self, k) -> int:
        from jepsen_tpu.txn import _hk
        hk = _hk(k)
        i = self._kid_of.get(hk)
        if i is None:
            i = self._kid_of[hk] = len(self.raw_key)
            self.raw_key.append(k)
        return i

    def absorb(self, i: int, op: dict) -> None:
        """Absorbs history op ``i``; mirrors the batch builder's event
        extraction + flatten passes exactly (sessions' differential
        fuzz pins it)."""
        typ = op.get("type")
        if typ not in ("invoke", "ok", "fail", "info"):
            return
        p = op.get("process")
        try:
            prev = self._last_ev.get(p)
        except TypeError:  # unhashable process: outside every regime
            self.fallback = self.fallback or "unhashable process"
            return
        self._last_ev[p] = (i, typ == "invoke")
        if typ == "invoke":
            return
        inv = prev[0] if (prev is not None and prev[1]) else None
        if typ == "fail":
            for m in op.get("value") or ():
                if m[0] == "append":
                    v = m[2]
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or not (0 <= v < self._max_val):
                        self.fallback = "non-int/overflow failed append"
                        return
                    self.f_kid.append(self.kid(m[1]))
                    self.f_val.append(v)
            return
        if not isinstance(p, int):
            return  # not a graph node (batch pint filter)
        cols = self.ok if typ == "ok" else self.info
        t = len(cols.pos)
        cols.pos.append(i)
        cols.inv.append(-1 if inv is None else inv)
        cols.proc.append(p)
        cols.txns.append(op)
        if self.fallback:
            return
        try:
            for mi, m in enumerate(op.get("value") or ()):
                if mi >= self._max_mops:
                    self.fallback = "over-long txn"
                    return
                f = m[0]
                if f == "append":
                    v = m[2]
                    if not isinstance(v, int) or isinstance(v, bool) \
                            or not (0 <= v < self._max_val):
                        self.fallback = "non-int/overflow append value"
                        return
                    cols.a_txn.append(t)
                    cols.a_kid.append(self.kid(m[1]))
                    cols.a_val.append(v)
                    cols.a_mi.append(mi)
                elif f == "r" and m[2] is not None:
                    cols.r_txn.append(t)
                    cols.r_kid.append(self.kid(m[1]))
                    cols.r_mi.append(mi)
                    cols.payloads.append(m[2] if type(m[2]) is list
                                         else list(m[2]))
        except (TypeError, ValueError, IndexError, OverflowError) as e:
            self.fallback = f"unflattenable txn: {e!r}"
