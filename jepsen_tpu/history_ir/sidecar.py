"""The history IR's ``.npz`` sidecar serialization.

``store.write_columnar``/``store.load_columnar`` delegate here: the
``history.npz`` sidecar next to ``history.jsonl`` *is* the serialized
:class:`~jepsen_tpu.history_ir.ir.DeviceHistory` — canonical packed
columns, the value intern table (each value canonical-JSON-encoded via
:mod:`jepsen_tpu.codec`), plus the derived view products that make
re-checks a pure array pipeline:

* ``elle_*`` — the Elle builder columns
  (:func:`jepsen_tpu.history_ir.views.elle_columns`), consumed by
  ``elle.columnar.check_columns``;
* ``lin_*`` — the register EventStream
  (:func:`~jepsen_tpu.history_ir.views.register_stream` through
  :func:`stream_to_columns`), consumed by
  ``checker.linearizable.check_stored``.

Because the view products are derived from the SAME IR the run's
checkers used (``history_ir.of`` memoizes per run), ``analyze``
re-checks and bench's stored-columns lane ride the same encode — the
sidecar is a cache of the IR, not a third encoder.

Sidecar schema (doc/performance.md "History IR"):

=================  ========================================================
key                contents
=================  ========================================================
``types``..        the canonical int columns (ir.CANONICAL_COLUMNS order);
``value_ids``      ``value_ids`` int32 into the intern table
``f_table``        object array of f names
``val_table``      object array of canonical-JSON-encoded intern values
                   (ids 1.., id 0 = None implicit); absent when any value
                   is not JSON-encodable
``elle_*``         Elle builder columns (integer regime only)
``lin_*``          register EventStream columns (register shape only)
=================  ========================================================
"""
from __future__ import annotations

import logging

import numpy as np

from jepsen_tpu.history import Intern
from jepsen_tpu.history_ir.ir import CANONICAL_COLUMNS, DeviceHistory

logger = logging.getLogger("jepsen.history_ir")


# ---------------------------------------------------------------------------
# intern-table round-trip (jepsen_tpu.codec owns the value encoding)
# ---------------------------------------------------------------------------


def intern_to_rows(intern: Intern) -> list[str] | None:
    """The intern table (ids 1..) as canonical-JSON rows, or None when
    any value isn't codec-encodable (the sidecar then omits the value
    columns; history.jsonl remains authoritative for values)."""
    from jepsen_tpu import codec
    rows = []
    for v in intern.table[1:]:
        try:
            rows.append(codec.encode(v).decode("utf-8"))
        except (TypeError, ValueError, UnicodeDecodeError):
            return None
    return rows


def intern_from_rows(rows) -> Intern:
    """Rebuilds the value Intern from :func:`intern_to_rows` output.

    Ids are POSITIONAL: each row appends at its own index, never
    deduplicates — two distinct ids whose canonical-JSON rows collide
    (a tuple and a list with equal contents, dicts differing only in
    key order) must keep their ids, or every ``value_ids`` entry after
    the collision would point at the wrong value. The lookup map gets
    the first occurrence, so later ``id()`` calls stay consistent.
    Round-trip pinned in tests/test_history_ir.py."""
    from jepsen_tpu import codec
    from jepsen_tpu.history_ir.ir import ValueIntern
    intern = ValueIntern()
    for row in rows:
        v = codec.decode(str(row).encode("utf-8"))
        i = len(intern.table)
        intern.table.append(v)
        try:
            intern._ids.setdefault(v, i)
        except TypeError:
            intern._ids.setdefault(("__unhashable__", repr(v)), i)
    return intern


# ---------------------------------------------------------------------------
# register EventStream <-> plain columns (the lin_* sidecar keys)
# ---------------------------------------------------------------------------


def stream_to_columns(stream) -> dict | None:
    """The stream as plain persistable arrays (the ``lin_*`` sidecar
    keys), or None when the intern table holds non-int values (beyond
    the id-0 None sentinel) — those can't round-trip through an int64
    column."""
    vals = stream.intern.table[1:]
    if not all(type(v) is int for v in vals):
        return None
    return {
        "kind": np.asarray(stream.kind, np.int8),
        "slot": np.asarray(stream.slot, np.int32),
        "f": np.asarray(stream.f, np.int32),
        "a": np.asarray(stream.a, np.int32),
        "b": np.asarray(stream.b, np.int32),
        "op_index": np.asarray(stream.op_index, np.int32),
        "n_slots": np.int64(stream.n_slots),
        "n_ops": np.int64(stream.n_ops),
        "intern_table": np.asarray(vals, np.int64),
    }


def stream_from_columns(cols: dict):
    """Rebuilds an EventStream from stream_to_columns' product."""
    from jepsen_tpu.checker.linear_encode import EventStream
    intern = Intern()
    for v in np.asarray(cols["intern_table"]).tolist():
        intern.id(int(v))
    return EventStream(
        kind=np.asarray(cols["kind"], np.int8),
        slot=np.asarray(cols["slot"], np.int32),
        f=np.asarray(cols["f"], np.int32),
        a=np.asarray(cols["a"], np.int32),
        b=np.asarray(cols["b"], np.int32),
        op_index=np.asarray(cols["op_index"], np.int32),
        n_slots=int(cols["n_slots"]),
        n_ops=int(cols["n_ops"]),
        intern=intern,
    )


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------


def derived_view_arrays(dh: DeviceHistory) -> dict:
    """The ``elle_*``/``lin_*`` view products worth persisting for this
    history's shape, derived through the IR's memoized views (so a run
    whose checkers already built them pays nothing here)."""
    from jepsen_tpu.history_ir import views
    extra: dict = {}
    try:
        ecols = views.elle_columns(dh)
        if ecols is not None:
            extra.update({f"elle_{k}": v for k, v in ecols.items()})
    except Exception:  # noqa: BLE001 - the sidecar is an optimization
        logger.warning("elle sidecar columns failed; omitting them",
                       exc_info=True)
    # single-register histories additionally persist the encoded
    # EventStream (lin_* keys) so linearizability re-checks skip the
    # jsonl + re-encoding (checker/linearizable.check_stored). Cheap
    # shape probe first: the encoder's pairing pre-pass is a full O(n)
    # walk and must not run on every non-register history
    from jepsen_tpu.store import first_client_f
    if first_client_f(dh.ops) in ("read", "write", "cas"):
        try:
            lcols = stream_to_columns(views.register_stream(dh))
            if lcols is not None:
                extra.update({f"lin_{k}": v for k, v in lcols.items()})
        except Exception:  # noqa: BLE001 - wrong shape after all
            logger.warning("register sidecar columns failed; omitting "
                           "them", exc_info=True)
    return extra


def save(path, dh: DeviceHistory) -> None:
    """Writes the IR (canonical columns + intern table + derived view
    products) as the ``history.npz`` sidecar at ``path``."""
    arrays = {name: getattr(dh, name) for name in CANONICAL_COLUMNS
              if getattr(dh, name) is not None}
    arrays["f_table"] = np.asarray(dh.f_table, dtype=object)
    rows = intern_to_rows(dh.intern)
    if rows is not None:
        arrays["val_table"] = np.asarray(rows, dtype=object)
    else:
        # values not JSON-encodable: the id column is meaningless
        # without its table
        arrays.pop("value_ids", None)
    arrays.update(derived_view_arrays(dh))
    np.savez_compressed(path, **arrays)


def load(path) -> DeviceHistory:
    """Reloads a sidecar as a DeviceHistory (sans Python op dicts —
    those live in history.jsonl). Archives from before the IR degrade
    gracefully: missing ``val_table`` loads an empty intern, missing
    ``f_table`` degrades to int f codes only."""
    with np.load(path, allow_pickle=True) as z:
        f_table = ([None if x is None else str(x) for x in z["f_table"]]
                   if "f_table" in z else [])
        intern = (intern_from_rows(z["val_table"])
                  if "val_table" in z else Intern())
        return DeviceHistory(
            types=z["types"], processes=z["processes"], fs=z["fs"],
            times=z["times"], indices=z["indices"],
            completion_of=z["completion_of"],
            invocation_of=z["invocation_of"],
            f_table=f_table,
            value_ids=(z["value_ids"] if "value_ids" in z else None),
            intern=intern)
