#!/usr/bin/env bash
# Control-node init: trust the db nodes' host keys, then idle so
# bin/console can exec in.
set -u
mkdir -p ~/.ssh
for i in $(seq 1 "${JEPSEN_NODE_COUNT:-5}"); do
  n="n$i"
  for _ in $(seq 1 30); do
    if ssh-keyscan -T 2 "$n" >> ~/.ssh/known_hosts 2>/dev/null; then
      break
    fi
    sleep 1
  done
done
echo "control node ready; db nodes: $(seq -s' ' -f 'n%g' 1 "${JEPSEN_NODE_COUNT:-5}")"
exec sleep infinity
