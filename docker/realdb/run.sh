#!/usr/bin/env bash
# One-shot `-m realdb` proof run against the compose fleet: up, wait,
# pytest with the ADDR env vars, transcript to realdb-transcript.txt,
# down. Run from the repo root or this directory; needs docker compose
# and network access to pull images (NOT available in the build image —
# run this on a workstation and commit the transcript).
set -euo pipefail
cd "$(dirname "$0")"

cleanup() { docker compose down -v --remove-orphans || true; }
trap cleanup EXIT

docker compose up -d --wait || {
    # --wait fails if any service lacks a healthcheck; fall back to a
    # fixed settle window for the ones without
    docker compose up -d
    echo "waiting 90s for services without healthchecks..."
    sleep 90
}

export JEPSEN_CASSANDRA_ADDR=127.0.0.1:9042
export JEPSEN_AEROSPIKE_ADDR=127.0.0.1:3000
export JEPSEN_AEROSPIKE_NS=test
export JEPSEN_RABBITMQ_ADDR=127.0.0.1:5672
export JEPSEN_RETHINKDB_ADDR=127.0.0.1:28015
export JEPSEN_MYSQL_ADDR=127.0.0.1:3306
export JEPSEN_HAZELCAST_ADDR=127.0.0.1:5701

cd ../..
python -m pytest tests/test_realdb.py -m realdb -v -rA \
    2>&1 | tee docker/realdb/realdb-transcript.txt
echo "transcript written to docker/realdb/realdb-transcript.txt"
