#!/usr/bin/env bash
# DB-node init: set the root password (shared via secret/node.env),
# allow root SSH logins, start sshd in the foreground.
set -u
echo "root:${ROOT_PASS:-jepsenpw}" | chpasswd
sed -i 's/^#\?PermitRootLogin.*/PermitRootLogin yes/' /etc/ssh/sshd_config
exec /usr/sbin/sshd -D
